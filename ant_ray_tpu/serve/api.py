"""Serve: deployments, replicas, routing, HTTP ingress.

Scaled-down mirror of the reference architecture (SURVEY §2.4 Serve /
§3.6): ``serve.run`` starts a named **controller actor** that reconciles
desired deployment state into **replica actors**; **handles** route calls
to replicas with power-of-two-choices over reported queue depths
(ref: serve/_private/router.py:472); an optional aiohttp **proxy actor**
exposes deployments over HTTP (ref: serve/_private/proxy.py).  Replicas
report ongoing-request counts, which also drive **queue-based
autoscaling** (ref: serve/_private/autoscaling_state.py), and
``@serve.batch`` coalesces concurrent calls into one model invocation
(ref: serve/batching.py).
"""

from __future__ import annotations

import collections
import contextvars
import functools
import itertools
import math
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable

from ant_ray_tpu.exceptions import (
    BackPressureError,
    DeadlineExceededError,
    GetTimeoutError,
)
from ant_ray_tpu.observability import tracing_plane
from ant_ray_tpu.observability.tracing_plane import TraceContext

CONTROLLER_NAME = "_serve_controller"


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


# ------------------------------------------------------------- observability

_METRICS: dict | None = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> dict:
    """Lazy ``art_serve_*`` instruments (PR 4 metrics plane: recorded to
    the GCS metrics table, exported by the dashboard's /metrics).  Lazy
    so importing serve never touches the worker runtime; emission is
    best-effort and a no-op outside a cluster."""
    global _METRICS
    if _METRICS is None:
        with _METRICS_LOCK:
            if _METRICS is None:
                from ant_ray_tpu.util.metrics import Counter, Gauge  # noqa: PLC0415

                _METRICS = {
                    "shed": Counter(
                        "art_serve_shed_requests_total",
                        "Requests shed by admission control / deadlines "
                        "(reason: backpressure|deadline)",
                        tag_keys=("deployment", "reason")),
                    "queue_depth": Gauge(
                        "art_serve_queue_depth",
                        "Sum of per-replica ongoing+queued requests",
                        tag_keys=("deployment",)),
                    "breaker": Gauge(
                        "art_serve_breaker_state",
                        "Per-replica circuit breaker state "
                        "(0=closed 1=half-open 2=open)",
                        tag_keys=("deployment", "replica")),
                    "suspect": Gauge(
                        "art_serve_suspect_replicas",
                        "Replicas ejected for repeated ongoing-poll "
                        "timeouts", tag_keys=("deployment",)),
                    "retries": Counter(
                        "art_serve_retries_total",
                        "Handle-level retries re-picked to another "
                        "replica", tag_keys=("deployment",)),
                    "retry_exhausted": Counter(
                        "art_serve_retry_budget_exhausted_total",
                        "Retries suppressed by an empty token bucket",
                        tag_keys=("deployment",)),
                }
    return _METRICS


def _emit(name: str, value: float, tags: dict) -> None:
    try:
        metric = _metrics()[name]
        if hasattr(metric, "inc"):
            metric.inc(value, tags)
        else:
            metric.set(value, tags)
    except Exception:  # noqa: BLE001 — observability must never fail a request
        pass


def _typed_cause(exc: BaseException):
    """Unwrap the typed overload error from an actor-task error chain
    (a replica-raised BackPressureError arrives as
    ``ActorError(cause=BackPressureError)``)."""
    for c in (exc, getattr(exc, "cause", None)):
        if isinstance(c, (BackPressureError, DeadlineExceededError)):
            return c
    return None


def _expire_replica_series(replica) -> None:
    """Drop a torn-down replica's per-replica gauges (the breaker-state
    series is tagged by replica id) from the GCS metrics table —
    without this every scaled-down or migrated replica haunts /metrics
    forever."""
    try:
        from ant_ray_tpu.api import global_worker  # noqa: PLC0415

        rt = global_worker.runtime
        rt._send_oneway(
            rt.gcs_address, "MetricsExpire",
            {"match_tags": {"replica": replica.actor_id.hex()[:12]}})
    except Exception:  # noqa: BLE001 — observability is best-effort
        pass


def _record_result(routing, replica, exc: BaseException | None = None):
    """Feed a request outcome into the replica's breaker.  Typed
    overload sheds are the admission gate speaking, not a health
    outcome; any other error (handler raise, actor death, connection
    loss) counts as a failure — per-replica corruption usually
    manifests as handler errors, and the ejection CAP
    (``max_eject_fraction``) is what protects a healthy fleet from a
    deterministic bad-input stream, not the error taxonomy."""
    if exc is not None and _typed_cause(exc) is not None:
        return
    routing.record_outcome(replica, exc is None)


# ---------------------------------------------------------------- public

@dataclass(frozen=True)
class AutoscalingConfig:
    """Queue-depth-driven replica scaling
    (ref: serve/_private/autoscaling_state.py + AutoscalingConfig)."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    # Seconds between controller scaling decisions.
    interval_s: float = 0.5
    # Consecutive low-load intervals required before scaling down
    # (downscale damping, ref: downscale_delay_s).
    downscale_patience: int = 4
    # Signal-targeted scaling: when set, the controller ALSO polls each
    # replica's ``load_signals()`` dict (e.g. the LLM engine loop's
    # art_llm_tokens_per_s / art_llm_queue_depth /
    # art_llm_resident_sessions gauges) and sizes the deployment so
    # sum(signal) / target_value replicas carry the load; the final
    # desired count is the max of the ongoing-based and signal-based
    # answers — queue depth still protects against a signal going
    # stale.  Replicas without a load_signals() method contribute 0.
    target_signal: str | None = None
    target_value: float = 1.0


@dataclass(frozen=True)
class RequestRetryConfig:
    """Opt-in handle-level retries for IDEMPOTENT handlers, bounded by
    a token-bucket retry budget (ref in spirit: the reference router's
    retryable-request semantics + SRE retry-budget practice).  Each
    completed request earns ``budget_fraction`` tokens (capped at
    ``budget_burst``); a retry spends one — a full outage can never
    amplify offered load by more than ~``budget_fraction``."""

    max_attempts: int = 3
    budget_fraction: float = 0.1
    budget_burst: float = 10.0
    # Also retry replica-side BackPressureError sheds on a different
    # replica (a re-pick, not a re-execution: the shed request never
    # ran).
    retry_backpressure: bool = True


@dataclass(frozen=True)
class CircuitBreakerConfig:
    """Per-replica circuit breaker in the router (ref capability:
    envoy-style outlier ejection; the reference routes around failing
    replicas via health checks).  Opens on failure rate over a sliding
    outcome window or on controller 'suspect' marks (repeated
    ongoing-poll timeouts); after ``cooldown_s`` one probation probe is
    allowed through (half-open) and a success closes the breaker."""

    window: int = 20
    min_outcomes: int = 5
    failure_rate: float = 0.5
    cooldown_s: float = 2.0
    # Ejection cap (envoy max_ejection_percent): failure-RATE opens
    # never eject more than this fraction of the replica set, so a
    # deterministic bad-input stream (which fails on EVERY replica)
    # cannot breaker-open a healthy deployment into a 429 outage.  A
    # single-replica deployment is never rate-ejected (cap rounds to
    # 0) — its errors surface to the client as themselves.  Liveness
    # (controller suspect) opens bypass the cap: a genuinely dead
    # replica must be ejected no matter how many already are.
    max_eject_fraction: float = 0.5


@dataclass
class Deployment:
    cls_or_fn: Any
    name: str
    num_replicas: int = 1
    route_prefix: str | None = None
    ray_actor_options: dict = field(default_factory=dict)
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)
    autoscaling_config: AutoscalingConfig | None = None
    # Redeploys replace replicas version-by-version, at most this many
    # extra replicas alive at once (ref: deployment_state.py:2597
    # rolling updates + max surge).
    rolling_max_surge: int = 1
    # ---- overload-resilience knobs (ref: DeploymentConfig
    # max_ongoing_requests / max_queued_requests + proxy
    # request_timeout_s).  None max_ongoing_requests = no admission
    # gate (legacy behavior).
    max_ongoing_requests: int | None = None
    max_queued_requests: int = 0
    request_timeout_s: float | None = None
    retry_config: RequestRetryConfig | None = None
    breaker_config: CircuitBreakerConfig | None = None

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, *, num_replicas: int | None = None,
                route_prefix: str | None = None,
                name: str | None = None,
                autoscaling_config: AutoscalingConfig | dict | None = None,
                rolling_max_surge: int | None = None,
                max_ongoing_requests: int | None = None,
                max_queued_requests: int | None = None,
                request_timeout_s: float | None = None,
                retry_config: "RequestRetryConfig | dict | None" = None,
                breaker_config: "CircuitBreakerConfig | dict | None" = None,
                ) -> "Deployment":
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        if isinstance(retry_config, dict):
            retry_config = RequestRetryConfig(**retry_config)
        if isinstance(breaker_config, dict):
            breaker_config = CircuitBreakerConfig(**breaker_config)
        return Deployment(
            cls_or_fn=self.cls_or_fn,
            name=name or self.name,
            num_replicas=num_replicas or self.num_replicas,
            route_prefix=(route_prefix if route_prefix is not None
                          else self.route_prefix),
            ray_actor_options=dict(self.ray_actor_options),
            init_args=self.init_args,
            init_kwargs=dict(self.init_kwargs),
            autoscaling_config=(autoscaling_config
                                or self.autoscaling_config),
            rolling_max_surge=(rolling_max_surge
                               if rolling_max_surge is not None
                               else self.rolling_max_surge),
            max_ongoing_requests=(max_ongoing_requests
                                  if max_ongoing_requests is not None
                                  else self.max_ongoing_requests),
            max_queued_requests=(max_queued_requests
                                 if max_queued_requests is not None
                                 else self.max_queued_requests),
            request_timeout_s=(request_timeout_s
                               if request_timeout_s is not None
                               else self.request_timeout_s),
            retry_config=retry_config or self.retry_config,
            breaker_config=breaker_config or self.breaker_config,
        )

    def overload_config(self) -> dict:
        """The routing-relevant knobs, pushed to every handle through
        the controller's long-poll channel."""
        return {
            "request_timeout_s": self.request_timeout_s,
            "retry": self.retry_config,
            "breaker": self.breaker_config or CircuitBreakerConfig(),
        }


@dataclass
class Application:
    deployment: Deployment
    args: tuple
    kwargs: dict


def deployment(_cls=None, *, name: str | None = None, num_replicas: int = 1,
               route_prefix: str | None = None,
               ray_actor_options: dict | None = None,
               autoscaling_config: AutoscalingConfig | dict | None = None,
               max_ongoing_requests: int | None = None,
               max_queued_requests: int = 0,
               request_timeout_s: float | None = None,
               retry_config: "RequestRetryConfig | dict | None" = None,
               breaker_config: "CircuitBreakerConfig | dict | None" = None):
    """``@serve.deployment`` decorator (ref: serve/api.py)."""
    if isinstance(autoscaling_config, dict):
        autoscaling_config = AutoscalingConfig(**autoscaling_config)
    if isinstance(retry_config, dict):
        retry_config = RequestRetryConfig(**retry_config)
    if isinstance(breaker_config, dict):
        breaker_config = CircuitBreakerConfig(**breaker_config)

    def wrap(cls_or_fn):
        return Deployment(
            cls_or_fn=cls_or_fn,
            name=name or getattr(cls_or_fn, "__name__", "deployment"),
            num_replicas=num_replicas,
            route_prefix=route_prefix,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling_config=autoscaling_config,
            max_ongoing_requests=max_ongoing_requests,
            max_queued_requests=max_queued_requests,
            request_timeout_s=request_timeout_s,
            retry_config=retry_config,
            breaker_config=breaker_config,
        )

    if _cls is not None:
        return wrap(_cls)
    return wrap


# How far AHEAD of the earliest request deadline the flusher fires: a
# flush at exactly the deadline would shed the item it was pulled
# forward for (the expiry check runs at flush time), so fire with this
# much runway for the model call to complete and the reply to ship.
_BATCH_FLUSH_MARGIN_S = 0.1


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """``@serve.batch``: coalesce concurrent single-item calls into one
    list call (ref: serve/batching.py).  The wrapped method must accept a
    LIST of items and return a LIST of results, one per item; callers
    still call it with a single item.  Requires the deployment to run
    with ``ray_actor_options={"max_concurrency": N}`` so calls can
    overlap inside the replica."""

    def wrap(fn):
        # Batch state lives on the INSTANCE (created lazily on first
        # call): a closure-level Lock would make the deployment class
        # unpicklable for shipping to replica workers.
        state_attr = f"_art_batch_state_{fn.__name__}"

        def get_state(self_obj):
            state = getattr(self_obj, state_attr, None)
            if state is None:
                cv = threading.Condition()
                state = self_obj.__dict__.setdefault(
                    state_attr, {"cv": cv, "items": []})
            return state

        def flush(self_obj, my_batch):
            # Deadline-aware flush: items whose end-to-end deadline
            # already expired are SHED (typed error, event set) without
            # ever reaching the model — executing them would waste a
            # model invocation slot on work nobody is waiting for.
            now = time.time()
            live = []
            for item, slot in my_batch:
                dl = slot["deadline_ts"]
                if dl is not None and now >= dl:
                    slot["result"] = DeadlineExceededError(
                        f"request deadline expired "
                        f"{now - dl:.3f}s before batch flush")
                    slot["event"].set()
                else:
                    live.append((item, slot))
            if not live:
                return
            items = [it for it, _ in live]
            try:
                results = fn(self_obj, items)
                if len(results) != len(items):
                    raise ValueError(
                        f"@serve.batch function returned {len(results)} "
                        f"results for {len(items)} items")
            except Exception as e:  # noqa: BLE001 — fan the error out
                results = [e] * len(items)
            for (_, slot), result in zip(live, results):
                slot["result"] = result
                slot["event"].set()

        def wrapper(self_obj, item):
            state = get_state(self_obj)
            cv = state["cv"]
            # NB: read the deadline via the module-level accessor, not
            # the ContextVar itself — this closure is cloudpickled by
            # value with the user's class, and ContextVars can't be
            # pickled (the accessor is resolved by reference).
            slot = {"event": threading.Event(), "result": None,
                    "deadline_ts": get_request_deadline()}
            with cv:
                state["items"].append((item, slot))
                is_flusher = len(state["items"]) == 1
                cv.notify_all()
            if is_flusher:
                # Event-driven wait (no polling tax): arrivals notify
                # the condition, so a full batch flushes the moment its
                # last item lands, and an item with a tight end-to-end
                # deadline pulls the flush forward so it is served
                # before it expires.
                wait_deadline = time.monotonic() + batch_wait_timeout_s
                with cv:
                    while len(state["items"]) < max_batch_size:
                        remaining = wait_deadline - time.monotonic()
                        req_deadline_ts = [
                            s["deadline_ts"] for _, s in state["items"]
                            if s["deadline_ts"] is not None]
                        if req_deadline_ts:
                            # Wall clock: deadline_ts is the wire field.
                            remaining = min(
                                remaining,
                                min(req_deadline_ts) - time.time()
                                - _BATCH_FLUSH_MARGIN_S)
                        if remaining <= 0:
                            break
                        cv.wait(remaining)
                # Drain in ≤max_batch_size chunks until empty: the model
                # never sees an oversized batch, and late arrivals that
                # saw a non-empty queue (so didn't become flushers) are
                # never stranded.
                while True:
                    with cv:
                        my_batch = state["items"][:max_batch_size]
                        state["items"] = state["items"][max_batch_size:]
                    if not my_batch:
                        break
                    flush(self_obj, my_batch)
            # Non-flushers wait for their batch-mate to flush; the
            # flusher's own event was set inside flush().
            slot["event"].wait()
            if isinstance(slot["result"], Exception):
                raise slot["result"]
            return slot["result"]

        wrapper.__name__ = fn.__name__
        wrapper.__wrapped__ = fn
        wrapper.__art_serve_batch__ = (max_batch_size,
                                       batch_wait_timeout_s)
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


# ------------------------------------------------------------ multiplexing

_multiplexed_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "serve_multiplexed_model_id", default="")

# Absolute (time.time) end-to-end deadline of the in-flight request,
# stamped by the ingress/handle and set by the replica around user-code
# invocation so nested machinery (@serve.batch, the LLM engine) can
# shed expired work instead of executing it.
_request_deadline: contextvars.ContextVar = contextvars.ContextVar(
    "serve_request_deadline", default=None)


def get_multiplexed_model_id() -> str:
    """Model id of the in-flight request, inside a replica method
    (ref: serve.get_multiplexed_model_id)."""
    return _multiplexed_model_id.get()


def get_request_deadline() -> float | None:
    """Absolute ``time.time()`` deadline of the in-flight request (None
    when the caller set no deadline), inside a replica method."""
    return _request_deadline.get()


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3):
    """Decorate a replica's model-loader method: per-replica LRU of
    loaded models, keyed by model id (ref: serve/_private/multiplex.py +
    @serve.multiplexed).  Callers steer requests with
    ``handle.options(multiplexed_model_id="m")``; the handle keeps
    model→replica affinity so one model isn't re-loaded on every
    replica (design note: affinity is handle-local here, where the
    reference shares replica model sets via controller long-poll — same
    steady state for any given caller, no extra control-plane chatter).
    """

    def wrap(fn):
        cache_attr = f"__serve_mux_cache_{fn.__name__}"
        lock_attr = f"__serve_mux_lock_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self_obj, model_id=None):
            if model_id is None:
                model_id = get_multiplexed_model_id()
            lock = getattr(self_obj, lock_attr, None)
            if lock is None:
                lock = threading.Lock()
                setattr(self_obj, lock_attr, lock)
            # One lock over lookup AND load: replicas run requests on a
            # thread pool, and two concurrent misses for one model must
            # not both run the loader (double model load = OOM with
            # real weights) or race the OrderedDict.
            with lock:
                cache = getattr(self_obj, cache_attr, None)
                if cache is None:
                    cache = collections.OrderedDict()
                    setattr(self_obj, cache_attr, cache)
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                model = fn(self_obj, model_id)
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    cache.popitem(last=False)  # LRU eviction
                return model

        wrapper.__serve_multiplexed__ = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap



class _Breaker:
    """Per-replica circuit state inside a routing family (closed →
    open → half-open → closed).  Mutated only under the routing lock."""

    __slots__ = ("state", "outcomes", "opened_at", "last_probe_at")

    def __init__(self, window: int):
        self.state = "closed"
        self.outcomes: collections.deque = collections.deque(
            maxlen=max(1, window))
        self.opened_at = 0.0
        self.last_probe_at = 0.0


class _RoutingState:
    """Replica set + queue snapshot shared by an options()-derived
    handle family, kept fresh by ONE controller long-poll listener
    thread (ref: serve/_private/long_poll.py LongPollClient).  The
    controller blocks the listen call until the deployment's version
    advances, so scale-ups/downs reach every handle within one push —
    no TTL staleness window.  A slow TTL poll remains as fallback for
    the window before the listener's first reply (or if it dies).

    Also owns the deployment's ROUTER RESILIENCE state: per-replica
    circuit breakers (opened by observed failure rate or by controller
    'suspect' marks from repeated ongoing-poll timeouts, re-entered via
    half-open probation probes) and the token-bucket retry budget."""

    def __init__(self, name: str, replicas: list, controller):
        self.lock = threading.Lock()
        self.name = name
        self.replicas = list(replicas)
        self.ongoing: list = [0] * len(replicas)
        self.local_extra: dict[int, int] = {}
        # -1 = "never synced": the first listen_for_change round trip
        # returns immediately with the deployment's CURRENT state —
        # critically the overload config (request_timeout_s / retry /
        # breaker) — instead of blocking until the next version bump.
        # Construction sites that already hold a get_handle_info
        # payload apply() it synchronously and skip this window.
        self.version = -1
        self.controller = controller
        self._listener: threading.Thread | None = None
        self._last_poll = time.monotonic()
        # Overload-plane config pushed by the controller (deployment
        # defaults); present before the first push so raw handles work.
        self.config: dict = {"request_timeout_s": None, "retry": None,
                             "breaker": CircuitBreakerConfig()}
        self.suspect: set = set()           # actor ids, controller-fed
        self.breakers: dict = {}            # actor id -> _Breaker
        self.retry_tokens: float | None = None

    def apply(self, info: dict) -> None:
        with self.lock:
            old_replicas = self.replicas
            old_extra = self.local_extra
            new_replicas = list(info["replicas"])
            # Carry this family's in-flight dispatch counts across the
            # update (remapped by replica identity): wiping them would
            # erase the load signal mid-burst and skew po2 routing.
            new_index = {r.actor_id: i
                         for i, r in enumerate(new_replicas)}
            extra: dict[int, int] = {}
            for index, count in old_extra.items():
                if index < len(old_replicas):
                    ni = new_index.get(old_replicas[index].actor_id)
                    if ni is not None:
                        extra[ni] = extra.get(ni, 0) + count
            self.replicas = new_replicas
            self.ongoing = list(info.get("ongoing",
                                         [0] * len(new_replicas)))
            self.local_extra = extra
            self.version = info.get("version", self.version)
            if info.get("config") is not None:
                self.config = info["config"]
            self._apply_suspects_locked(
                set(info.get("suspect", ()) or ()), set(new_index))
        self._last_poll = time.monotonic()

    # ------------------------------------------------- circuit breakers

    def _apply_suspects_locked(self, new_suspect: set, live: set) -> None:
        """Controller liveness verdicts are authoritative: a replica
        whose ongoing polls time out repeatedly is force-opened (sticky
        while suspect); when the controller's poll succeeds again the
        breaker drops to half-open so the next request is a probation
        probe, not a stampede."""
        new_suspect &= live
        now = time.monotonic()
        for aid in new_suspect - self.suspect:
            br = self._breaker_locked(aid)
            if br.state != "open":
                self._set_state_locked(aid, br, "open")
                br.opened_at = now
        for aid in self.suspect - new_suspect:
            br = self.breakers.get(aid)
            if br is not None and br.state == "open":
                self._set_state_locked(aid, br, "half_open")
                br.last_probe_at = 0.0
        self.suspect = new_suspect
        for aid in list(self.breakers):
            if aid not in live:
                del self.breakers[aid]

    def _breaker_locked(self, aid) -> _Breaker:
        br = self.breakers.get(aid)
        if br is None:
            br = _Breaker(self.config["breaker"].window)
            self.breakers[aid] = br
        return br

    def _set_state_locked(self, aid, br: _Breaker, state: str) -> None:
        br.state = state
        _emit("breaker", {"closed": 0, "half_open": 1, "open": 2}[state],
              {"deployment": self.name, "replica": aid.hex()[:12]})

    def _probe_due_locked(self, aid, br: _Breaker, now: float) -> bool:
        """True when an ejected replica has earned its probation probe:
        never while the controller still suspects it, and at most one
        probe per cooldown interval."""
        if aid in self.suspect:
            return False
        cooldown = self.config["breaker"].cooldown_s
        if br.state == "open":
            if now - br.opened_at < cooldown:
                return False
            self._set_state_locked(aid, br, "half_open")
            br.last_probe_at = 0.0
        return now - br.last_probe_at >= cooldown

    def record_outcome(self, replica, ok: bool) -> None:
        """Feed a request outcome (observed wherever results are read:
        handle.call(), the ingresses) into the replica's breaker and
        earn retry-budget tokens."""
        with self.lock:
            rcfg = self.config.get("retry")
            if rcfg is not None:
                if self.retry_tokens is None:
                    self.retry_tokens = float(rcfg.budget_burst)
                self.retry_tokens = min(float(rcfg.budget_burst),
                                        self.retry_tokens
                                        + rcfg.budget_fraction)
            aid = replica.actor_id
            br = self._breaker_locked(aid)
            if br.state != "closed":
                # Only a HALF-OPEN success closes the breaker: a
                # success landing while still "open" is a stale
                # in-flight request dispatched before the trip, not a
                # probation verdict — closing on it would bypass the
                # cooldown and flap the breaker under concurrent
                # traffic.  Failures always (re-)open.
                if (ok and br.state == "half_open"
                        and aid not in self.suspect):
                    self._set_state_locked(aid, br, "closed")
                    br.outcomes.clear()
                elif not ok:
                    self._set_state_locked(aid, br, "open")
                    br.opened_at = time.monotonic()
                return
            br.outcomes.append(ok)
            if ok:
                return
            bcfg = self.config["breaker"]
            fails = sum(1 for o in br.outcomes if not o)
            if (len(br.outcomes) >= bcfg.min_outcomes
                    and fails / len(br.outcomes) >= bcfg.failure_rate):
                # Ejection cap: rate-driven opens stop once the open
                # share would exceed max_eject_fraction — a failure
                # mode shared by EVERY replica (bad input) then keeps
                # most of the fleet routable (suspect/liveness opens
                # bypass this in _apply_suspects_locked).
                already_open = sum(1 for o in self.breakers.values()
                                   if o.state == "open")
                cap = int(bcfg.max_eject_fraction * len(self.replicas))
                if already_open < cap:
                    self._set_state_locked(aid, br, "open")
                    br.opened_at = time.monotonic()

    def take_retry_token(self) -> bool:
        with self.lock:
            rcfg = self.config.get("retry")
            if rcfg is None:
                return False
            if self.retry_tokens is None:
                self.retry_tokens = float(rcfg.budget_burst)
            if self.retry_tokens >= 1.0:
                self.retry_tokens -= 1.0
                return True
            return False

    def default_timeout(self) -> float | None:
        return self.config.get("request_timeout_s")

    def ensure_listener(self) -> None:
        if self.controller is None or self._listener is not None:
            return
        with self.lock:
            if self._listener is not None:
                return
            self._listener = threading.Thread(
                target=self._listen_loop, daemon=True,
                name=f"serve-listen-{self.name}")
        self._listener.start()

    def _listen_loop(self) -> None:
        art = _art()
        while True:
            try:
                changed = art.get(
                    self.controller.listen_for_change.remote(
                        {self.name: self.version}),
                    timeout=_LISTEN_TIMEOUT_S + 15)
            except Exception:  # noqa: BLE001 — controller restarting
                time.sleep(0.5)
                continue
            if not changed:
                continue                       # listen timeout: re-arm
            info = changed.get(self.name)
            if info is None:
                return                         # deployment deleted
            self.apply(info)

    def poll_fallback(self) -> None:
        """TTL refresh for the pre-listener window (and as a safety net
        if the push channel wedges)."""
        if self.controller is None:
            return
        if time.monotonic() - self._last_poll < \
                DeploymentHandle._REFRESH_TTL_S:
            return
        self._last_poll = time.monotonic()
        try:
            info = _art().get(
                self.controller.get_handle_info.remote(self.name))
        except Exception:  # noqa: BLE001 — keep the cached set
            return
        if info:
            self.apply(info)


# Controller-side long-poll window; client waits a bit longer.
_LISTEN_TIMEOUT_S = 30.0

# Ongoing-poll liveness: per-replica answer budget, and how many
# consecutive failed polls make a replica SUSPECT (force-opens its
# breaker in every handle).  ~3 × (0.25s loop + 2s budget) ≈ a wedge is
# ejected within ~7s of going dark.
_POLL_TIMEOUT_S = 2.0
_POLL_STRIKE_LIMIT = 3


class DeploymentHandle:
    """Client handle routing calls across a deployment's replicas with
    power-of-two-choices over reported queue depths
    (ref: PowerOfTwoChoicesRequestRouter, serve/_private/router.py:472).

    Replica-set changes are PUSHED: a listener long-polls the
    controller's version channel and rewrites the shared routing state
    the moment a deployment scales (ref: serve/_private/long_poll.py
    LongPollClient) — a scale-up is visible to the very next request,
    not after a TTL.  A slow TTL poll remains as the fallback when the
    listener cannot run."""

    _REFRESH_TTL_S = 30.0           # fallback only — push is primary

    def __init__(self, deployment_name: str, replicas: list,
                 method_name: str = "__call__", stream: bool = False,
                 controller=None, multiplexed_model_id: str = "",
                 _mux_affinity: dict | None = None,
                 _routing: "_RoutingState | None" = None,
                 _info: dict | None = None,
                 trace_ctx: "TraceContext | None" = None):
        self._name = deployment_name
        self._method = method_name
        self._stream = stream
        self._controller = controller
        self._mux_model_id = multiplexed_model_id
        # Bound trace context (serve composition: a handle created
        # inside a traced request and pickled into a downstream
        # deployment joins that trace when no ambient context is set;
        # the sampled flag survives the pickle via __reduce__).
        self._trace_ctx = trace_ctx
        # model id -> replica; SHARED with handles derived via
        # options() so affinity survives per-request option changes
        self._mux_affinity = ({} if _mux_affinity is None
                              else _mux_affinity)
        self._rr = itertools.count()
        # Routing state (replica set + queue snapshot) is shared across
        # the options()-derived handle family: one listener serves all.
        self._routing = (_routing if _routing is not None
                         else _RoutingState(deployment_name, replicas,
                                            controller))
        if _info is not None and _routing is None:
            # Seed the overload config (deadline default, retry budget,
            # breaker knobs) synchronously from the construction-time
            # get_handle_info payload — the very first call must honor
            # request_timeout_s, not wait for the listener's push.
            self._routing.apply(_info)
        # Arm the push listener NOW, not on first use: a scale-down can
        # kill a replica from this handle's constructor-time list before
        # the first request, and the drain grace assumes every live
        # handle hears about shrinks promptly.
        self._routing.ensure_listener()

    def options(self, method_name: str | None = None,
                stream: bool | None = None,
                multiplexed_model_id: str | None = None
                ) -> "DeploymentHandle":
        """``stream=True``: remote() returns an ObjectRefGenerator whose
        refs arrive as the replica's generator produces them
        (ref: handle.options(stream=True)).  ``multiplexed_model_id``
        routes to the replica that already serves that model."""
        return DeploymentHandle(
            self._name, self._routing.replicas,
            method_name if method_name is not None else self._method,
            self._stream if stream is None else stream,
            self._controller,
            (self._mux_model_id if multiplexed_model_id is None
             else multiplexed_model_id),
            self._mux_affinity,
            self._routing)

    # Internal views over the shared routing state (kept as properties
    # so the routing/mux logic below reads naturally).
    @property
    def _lock(self):
        return self._routing.lock

    @property
    def _replicas(self):
        return self._routing.replicas

    @property
    def _ongoing(self):
        return self._routing.ongoing

    @property
    def _local_extra(self):
        return self._routing.local_extra

    def _maybe_refresh(self):
        if self._routing.version < 0 and self._controller is not None:
            # Never-synced routing state (a handle reconstructed from a
            # pickle — serve composition embeds handles in downstream
            # deployments' args): the overload config must govern the
            # FIRST dispatch, so fetch it synchronously once instead of
            # racing the listener's first push.
            try:
                info = _art().get(
                    self._controller.get_handle_info.remote(self._name),
                    timeout=5)
            except Exception:  # noqa: BLE001 — poll fallback covers it
                pass
            else:
                if info is not None:
                    self._routing.apply(info)
        self._routing.ensure_listener()
        self._routing.poll_fallback()

    def _pick(self, exclude: set | None = None):
        """Two random candidates among breaker-ALLOWED replicas, route
        to the shorter queue (cached depth + dispatches this handle made
        since the last refresh).  An ejected replica due for its
        probation probe is chosen deliberately (exactly one request per
        cooldown) so breakers can close again; if every replica is
        ejected the caller gets a typed BackPressureError instead of a
        request lobbed at a known-bad replica.  Returns the replica
        HANDLE, resolved inside the critical section — the listener
        thread may swap the replica list at any moment, so an index is
        stale the instant the lock drops."""
        with self._lock:
            routing = self._routing
            n = len(self._replicas)
            if n == 0:
                raise RuntimeError(
                    f"deployment {self._name} has no replicas")
            now = time.monotonic()
            candidates = []
            for k in range(n):
                aid = self._replicas[k].actor_id
                if exclude and aid in exclude:
                    continue
                br = routing.breakers.get(aid)
                if br is None or br.state == "closed":
                    candidates.append(k)
                elif routing._probe_due_locked(aid, br, now):
                    # Probation probe: route THIS request to it.
                    br.last_probe_at = now
                    self._local_extra[k] = self._local_extra.get(k, 0) + 1
                    return self._replicas[k]
            if not candidates:
                cooldown = routing.config["breaker"].cooldown_s
                remaining = [max(0.0, cooldown - (now - br.opened_at))
                             for br in routing.breakers.values()
                             if br.state == "open"]
                raise BackPressureError(
                    f"deployment {self._name}: all replicas unavailable "
                    "(circuit open / excluded)",
                    retry_after_s=min(remaining, default=1.0))
            if len(candidates) == 1:
                index = candidates[0]
            else:
                i, j = random.sample(candidates, 2)

                def load(k):
                    depth = (self._ongoing[k]
                             if k < len(self._ongoing) else 0)
                    return depth + self._local_extra.get(k, 0)

                index = i if load(i) <= load(j) else j
            self._local_extra[index] = \
                self._local_extra.get(index, 0) + 1
            return self._replicas[index]

    def _trace_root(self) -> "TraceContext":
        """The request's trace identity at this handle: the ambient
        context (a proxy ingress or an enclosing traced task), the
        handle's pickled binding, or — ``handle.call``/``remote()``
        being an ingress themselves — a freshly minted head-sampled
        root."""
        return (tracing_plane.current() or self._trace_ctx
                or tracing_plane.mint())

    def _request_meta(self, timeout_s: float | None = None,
                      trace: "TraceContext | None" = None) -> dict:
        """Stamp what rides to the replica: the end-to-end deadline (an
        explicit per-call timeout wins, else the deployment's
        ``request_timeout_s`` default pushed by the controller) and the
        trace context.  The trace travels even when UNSAMPLED — a shed
        (429/504) on the replica force-samples an error span and needs
        the request's trace id to hang it off."""
        meta: dict = {}
        timeout = (timeout_s if timeout_s is not None
                   else self._routing.default_timeout())
        # NB: 0 is a real (already-expired) deadline — a gRPC client
        # whose native deadline just hit zero must be shed, not granted
        # unbounded time.
        if timeout is not None:
            meta["deadline_ts"] = time.time() + float(timeout)
        meta["trace"] = (trace if trace is not None
                         else self._trace_root()).to_wire()
        return meta

    def _dispatch(self, replica, args, kwargs, model_id: str,
                  meta: dict | None):
        # Scope the request's trace over the actor submission so the
        # task spec inherits it (the replica-side execution span nests
        # under this request, not under whatever the dispatching thread
        # happened to be doing).
        wire = (meta or {}).get("trace")
        if wire is None:
            if self._stream:
                return replica.handle_request_streaming.remote(
                    self._method, args, kwargs, model_id, meta)
            return replica.handle_request.remote(
                self._method, args, kwargs, model_id, meta)
        with tracing_plane.use(TraceContext.from_wire(wire)):
            if self._stream:
                return replica.handle_request_streaming.remote(
                    self._method, args, kwargs, model_id, meta)
            return replica.handle_request.remote(
                self._method, args, kwargs, model_id, meta)

    def _pick_affine(self, exclude: set | None = None):
        """``_pick`` honoring multiplexed-model affinity.  Affinity is
        by replica IDENTITY: handles refresh their replica lists
        independently, so a stored index could point at a different
        replica after a resize.  The remembered replica is skipped when
        it is retry-excluded or breaker-ejected — the re-pick then
        migrates the affinity (one model reload beats routing into a
        known-bad replica)."""
        model_id = self._mux_model_id
        if not model_id:
            return self._pick(exclude=exclude)
        with self._lock:
            target = self._mux_affinity.get(model_id)
            if target is not None and not (exclude
                                           and target.actor_id in exclude):
                br = self._routing.breakers.get(target.actor_id)
                if br is None or br.state == "closed":
                    for r in self._replicas:
                        if r.actor_id == target.actor_id:
                            return r
        replica = self._pick(exclude=exclude)
        with self._lock:
            self._mux_affinity[model_id] = replica
        return replica

    def remote(self, *args, **kwargs):
        self._maybe_refresh()
        replica = self._pick_affine()
        return self._dispatch(replica, args, kwargs, self._mux_model_id,
                              self._request_meta())

    def call(self, *args, timeout_s: float | None = None, **kwargs):
        """Blocking dispatch with the full resilience contract: the
        deadline bounds the WHOLE request (queueing included), queued
        work past deadline is cancelled via ``art.cancel`` so it never
        executes, outcomes feed the per-replica circuit breakers, and —
        when the deployment opts in via ``retry_config`` (idempotent
        handlers only) — failures re-pick a different replica under the
        token-bucket retry budget.  The ingresses route through here;
        ``remote()`` stays the raw ref-returning path.

        Tracing: ``call`` is an ingress — a root context is minted when
        none is ambient, a ``route:{deployment}`` span covers
        pick + dispatch + reply, and shed outcomes (429/504) are
        force-sampled error spans even on unsampled requests."""
        root = self._trace_root()
        route_ctx = root.child()
        t_wall = time.time()
        t0 = time.perf_counter()
        exc: BaseException | None = None
        try:
            with tracing_plane.use(route_ctx):
                return self._call_impl(route_ctx, timeout_s, args,
                                       kwargs)
        except BaseException as e:
            exc = e
            raise
        finally:
            typed = _typed_cause(exc) if exc is not None else None
            attrs = {"deployment": self._name}
            if typed is not None:
                attrs["shed"] = type(typed).__name__
            tracing_plane.record_span(
                root, f"route:{self._name}", ts=t_wall,
                dur_s=time.perf_counter() - t0, attrs=attrs,
                error=exc is not None, span_id=route_ctx.span_id,
                parent_id=root.span_id, service="router")

    def _call_impl(self, route_ctx, timeout_s, args, kwargs):
        art = _art()
        self._maybe_refresh()
        rcfg = self._routing.config.get("retry")
        timeout = (timeout_s if timeout_s is not None
                   else self._routing.default_timeout())
        # Wall clock BY DESIGN: this becomes the request's cross-process
        # deadline_ts wire field, the one clock every host shares.
        deadline_ts = (time.time() + float(timeout)
                       if timeout is not None else None)
        attempts = rcfg.max_attempts if rcfg is not None else 1
        exclude: set = set()
        last_exc: Exception | None = None
        for attempt in range(max(1, attempts)):
            if deadline_ts is not None and time.time() >= deadline_ts:
                raise last_exc or DeadlineExceededError(
                    f"deadline expired before dispatch to {self._name}")
            try:
                replica = self._pick_affine(exclude=exclude)
            except BackPressureError:
                if last_exc is not None:
                    # A retry that excluded every replica (e.g. a
                    # single-replica deployment): surface the REAL
                    # failure, not a misleading retriable 429.
                    raise last_exc from None
                raise
            meta: dict = {"trace": route_ctx.to_wire()}
            if deadline_ts is not None:
                meta["deadline_ts"] = deadline_ts
            ref = self._dispatch(replica, args, kwargs,
                                 self._mux_model_id, meta)
            try:
                remaining = (None if deadline_ts is None
                             else max(0.0, deadline_ts - time.time()))
                result = art.get(ref, timeout=remaining)
            except GetTimeoutError:
                # The deadline fired while the call was queued or
                # running.  Cancel reaps it if it has not started —
                # expired work is shed, not executed; running work
                # cannot be preempted and is left to finish into the
                # void.  Not a breaker outcome: slowness under load is
                # the admission gate's problem, ejection is for
                # *broken* replicas (errors / liveness strikes).
                try:
                    art.cancel(ref)
                except Exception:  # noqa: BLE001 — best-effort reap
                    pass
                raise DeadlineExceededError(
                    f"{self._name}: no reply within {timeout}s "
                    f"(attempt {attempt + 1})") from None
            except Exception as e:  # noqa: BLE001 — classified below
                typed = _typed_cause(e)
                if isinstance(typed, DeadlineExceededError):
                    raise typed  # replica shed expired work; no retry
                if isinstance(typed, BackPressureError):
                    last_exc = typed
                    retryable = (rcfg is not None
                                 and rcfg.retry_backpressure)
                else:
                    _record_result(self._routing, replica, e)
                    last_exc = e
                    retryable = rcfg is not None
                if not retryable or attempt >= attempts - 1:
                    raise last_exc
                if not self._routing.take_retry_token():
                    _emit("retry_exhausted", 1,
                          {"deployment": self._name})
                    raise last_exc
                exclude.add(replica.actor_id)
                _emit("retries", 1, {"deployment": self._name})
                continue
            self._routing.record_outcome(replica, True)
            return result
        raise last_exc  # pragma: no cover — loop always returns/raises

    def with_trace_context(self, ctx: "TraceContext | None"
                           ) -> "DeploymentHandle":
        """A handle whose dispatches join ``ctx`` when no ambient trace
        context is set — the explicit binding for serve composition
        (pass the bound handle in a downstream deployment's args; the
        sampled flag survives the pickle)."""
        return DeploymentHandle(
            self._name, self._routing.replicas, self._method,
            self._stream, self._controller, self._mux_model_id,
            self._mux_affinity, self._routing, trace_ctx=ctx)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._name, self._replicas, self._method, self._stream,
                 self._controller, self._mux_model_id,
                 None, None, None, self._trace_ctx))


# ---------------------------------------------------------------- actors

class Replica:
    """One replica actor wrapping the user's callable/class
    (ref: serve/_private/replica.py:1124).

    ADMISSION CONTROL lives here, replica-side, where the bound is
    enforceable no matter how many handles/proxies dispatch (client-side
    counting can always over-admit under fan-in): at most
    ``max_ongoing_requests`` invocations execute user code concurrently,
    at most ``max_queued_requests`` more may wait for a slot, and the
    rest fast-fail with a typed :class:`BackPressureError` (429 /
    RESOURCE_EXHAUSTED at the ingresses).  Queued work whose stamped
    end-to-end deadline expires while waiting is SHED — never executed
    (ref: DeploymentConfig.max_ongoing_requests/max_queued_requests)."""

    def __init__(self, cls_or_fn, args, kwargs, limits: dict | None = None):
        if isinstance(cls_or_fn, type):
            self._instance = cls_or_fn(*args, **kwargs)
        else:
            self._instance = cls_or_fn  # plain function deployment
        limits = limits or {}
        self._deployment = limits.get("deployment", "")
        self._max_ongoing = limits.get("max_ongoing_requests")
        self._max_queued = int(limits.get("max_queued_requests", 0) or 0)
        # One condition guards _running (user code executing now) and
        # the FIFO wait line (_waiters: one opaque token per queued
        # request, head owns the next freed slot).
        self._admit_cv = threading.Condition()
        self._running = 0
        self._waiters: collections.deque = collections.deque()
        # EWMA of service seconds — the basis for the Retry-After hint
        # (how long until a slot plausibly frees).
        self._ewma_service_s = 0.05

    # ------------------------------------------------------ admission

    def _retry_after_locked(self) -> float:
        """Server-side hint: roughly one service time per request that
        must drain before new capacity appears."""
        waiting = len(self._waiters) + 1
        slots = max(1, self._max_ongoing or 1)
        return max(0.05, self._ewma_service_s * waiting / slots)

    def _admit(self, deadline_ts: float | None):
        """Block until a user-code slot frees (bounded FIFO queue), or
        shed: BackPressureError when the queue is full,
        DeadlineExceededError when the deadline expires while queued.
        No-op (count only) when the deployment sets no bound (legacy
        behavior)."""
        with self._admit_cv:
            if self._max_ongoing is None:
                self._running += 1
                return
            # Barge-free FIFO: with waiters present a fresh arrival
            # lines up behind them even if a slot just freed (the head
            # waiter owns it) — else a steady arrival stream starves a
            # queued request into a deadline shed FIFO would have
            # served.  The head check in the wait loop enforces it: a
            # non-head waiter that wakes first goes back to sleep.
            if self._running < self._max_ongoing and not self._waiters:
                self._running += 1
                return
            if len(self._waiters) >= self._max_queued:
                _emit("shed", 1, {"deployment": self._deployment,
                                  "reason": "backpressure"})
                raise BackPressureError(
                    f"replica at capacity ({self._running} running, "
                    f"{len(self._waiters)} queued)",
                    retry_after_s=self._retry_after_locked())
            token = object()
            self._waiters.append(token)
            try:
                while (self._running >= self._max_ongoing
                       or self._waiters[0] is not token):
                    remaining = (None if deadline_ts is None
                                 else deadline_ts - time.time())
                    if remaining is not None and remaining <= 0:
                        _emit("shed", 1,
                              {"deployment": self._deployment,
                               "reason": "deadline"})
                        raise DeadlineExceededError(
                            "deadline expired while queued for a "
                            "replica slot — request shed, not "
                            "executed")
                    self._admit_cv.wait(remaining)
            except BaseException:
                self._waiters.remove(token)
                # This waiter may have consumed a wakeup meant for a
                # sibling (and its exit may promote a new head): pass
                # it on or a queued request sleeps forever beside a
                # free slot.
                self._admit_cv.notify_all()
                raise
            self._waiters.remove(token)
            self._running += 1

    def _release(self, started: float) -> None:
        with self._admit_cv:
            self._running -= 1
            elapsed = time.monotonic() - started
            self._ewma_service_s += 0.2 * (elapsed - self._ewma_service_s)
            # notify_all, not notify: only the FIFO head may take the
            # slot, and a single notify could land on a non-head waiter
            # (which re-sleeps), stranding the head.  Wait lines are
            # bounded by max_queued, so the herd is small.
            self._admit_cv.notify_all()

    def _check_deadline(self, deadline_ts: float | None) -> None:
        if deadline_ts is not None and time.time() >= deadline_ts:
            _emit("shed", 1, {"deployment": self._deployment,
                              "reason": "deadline"})
            raise DeadlineExceededError(
                "request deadline expired before execution — shed, "
                "not executed")

    # ------------------------------------------------------ dispatch

    def _invoke(self, method_name: str, args, kwargs, model_id: str = "",
                deadline_ts: float | None = None):
        token = _multiplexed_model_id.set(model_id) if model_id else None
        dl_token = _request_deadline.set(deadline_ts)
        try:
            if method_name == "__call__":
                return self._instance(*args, **kwargs)
            return getattr(self._instance, method_name)(*args, **kwargs)
        finally:
            _request_deadline.reset(dl_token)
            if token is not None:
                _multiplexed_model_id.reset(token)

    def _trace_exec_ctx(self, meta: dict | None):
        """(exec_ctx, parent_span_id) for this request, or (None, "").
        Prefers the ambient context (the worker executor set it from
        the task spec on sampled requests — nesting the replica span
        under the execution span); falls back to the meta-carried wire
        context, which travels even UNSAMPLED so shed error spans can
        be force-sampled under the request's trace id."""
        parent = tracing_plane.current()
        if parent is None:
            parent = TraceContext.from_wire((meta or {}).get("trace"))
        if parent is None:
            return None, ""
        return parent.child(), parent.span_id

    def handle_request(self, method_name: str, args, kwargs,
                       model_id: str = "", meta: dict | None = None):
        """One admission sequence for traced and untraced requests —
        the trace hooks are no-ops without a context; with one the span
        covers admission (queue stage) + execution and sheds record
        force-sampled error spans."""
        deadline_ts = (meta or {}).get("deadline_ts")
        exec_ctx, parent_span = self._trace_exec_ctx(meta)
        t_wall = time.time()
        t0 = time.perf_counter()
        token = (tracing_plane.set_current(exec_ctx)
                 if exec_ctx is not None else None)
        err: BaseException | None = None
        t_admit = t0
        try:
            try:
                self._check_deadline(deadline_ts)  # shed before queueing
                self._admit(deadline_ts)           # bounded queue / shed
            finally:
                # Stamped even when _admit sheds: a request that waited
                # 2s in the queue before its 429/504 attributes those
                # 2s to the queue stage, not to execute.
                t_admit = time.perf_counter()
            started = time.monotonic()
            try:
                self._check_deadline(deadline_ts)  # shed before execution
                return self._invoke(method_name, args, kwargs, model_id,
                                    deadline_ts)
            finally:
                self._release(started)
        except BaseException as e:
            err = e
            raise
        finally:
            if token is not None:
                tracing_plane.reset(token)
            if exec_ctx is not None:
                self._record_request_span(
                    exec_ctx, parent_span, method_name, t_wall, t0,
                    t_admit, err)

    def _record_request_span(self, exec_ctx, parent_span, method_name,
                             t_wall, t0, t_admit, err) -> None:
        now = time.perf_counter()
        attrs = {"deployment": self._deployment, "method": method_name}
        if err is not None and isinstance(
                err, (BackPressureError, DeadlineExceededError)):
            attrs["shed"] = type(err).__name__
        stages = {"queue": max(0.0, t_admit - t0),
                  "execute": max(0.0, now - max(t_admit, t0))}
        tracing_plane.record_span(
            exec_ctx, f"replica:{self._deployment or 'replica'}",
            ts=t_wall, dur_s=now - t0, stages=stages, attrs=attrs,
            error=err is not None, span_id=exec_ctx.span_id,
            parent_id=parent_span, service="replica")

    def handle_request_streaming(self, method_name: str, args, kwargs,
                                 model_id: str = "",
                                 meta: dict | None = None):
        """Streaming dispatch: the target method must return a generator;
        its items flow back as a streaming actor call.  The ongoing
        count covers the WHOLE stream — a replica mid-generation must
        look busy to routing and must not be an autoscaler down-scale
        victim."""
        deadline_ts = (meta or {}).get("deadline_ts")
        exec_ctx, parent_span = self._trace_exec_ctx(meta)
        t_wall = time.time()
        t0 = time.perf_counter()
        t_admit = t0
        err: BaseException | None = None
        trace_token = (tracing_plane.set_current(exec_ctx)
                       if exec_ctx is not None else None)
        try:
            try:
                self._check_deadline(deadline_ts)
                self._admit(deadline_ts)
            finally:
                t_admit = time.perf_counter()  # queue stage incl. sheds
            started = time.monotonic()
            # Tokens span the WHOLE stream: the generator body runs
            # during iteration, long after _invoke (which only creates
            # it, with the same context) has returned.
            token = (_multiplexed_model_id.set(model_id) if model_id
                     else None)
            dl_token = _request_deadline.set(deadline_ts)
            try:
                yield from self._invoke(method_name, args, kwargs,
                                        model_id, deadline_ts)
            finally:
                _request_deadline.reset(dl_token)
                if token is not None:
                    _multiplexed_model_id.reset(token)
                self._release(started)
        except BaseException as e:
            err = e
            raise
        finally:
            if trace_token is not None:
                tracing_plane.reset(trace_token)
            if exec_ctx is not None:
                # GeneratorExit (consumer abandoned the stream) is a
                # normal ending, not a replica failure.
                failed = err is not None and not isinstance(
                    err, GeneratorExit)
                self._record_request_span(
                    exec_ctx, parent_span, method_name, t_wall, t0,
                    t_admit, err if failed else None)

    def ongoing(self) -> int:
        """Queue-depth metric feeding autoscaling and po2 routing
        (ref: replica queue-length metrics, autoscaling_state.py):
        executing AND queued — an admitted-but-waiting request is load
        the router must see."""
        return self._running + len(self._waiters)

    def load_signals(self) -> dict:
        """Deployment-defined load gauges for signal-targeted
        autoscaling (`AutoscalingConfig.target_signal`): delegates to
        the wrapped instance's ``load_signals()`` if it has one (the
        LLM engine loop publishes tokens/s, queue depth, and resident
        sessions this way)."""
        fn = getattr(self._instance, "load_signals", None)
        if callable(fn):
            try:
                return dict(fn())
            except Exception:  # noqa: BLE001 — a gauge blip isn't fatal
                return {}
        return {}

    def health(self):
        return "ok"


# Streaming marker on the dispatch method (equivalent of decorating with
# @art.method(num_returns="streaming") without importing art at module
# import time).
Replica.handle_request_streaming.__art_num_returns__ = "streaming"


class ServeController:
    """Reconciles deployments → replica actors; a background thread polls
    replica queue depths and drives queue-based autoscaling
    (ref: serve/_private/controller.py:105 + autoscaling_state.py)."""

    def __init__(self):
        self._deployments: dict[str, dict] = {}
        self._proxy = None
        self._lock = threading.Lock()
        # Long-poll version channel: listeners block here until some
        # deployment's version advances (ref: serve/_private/
        # long_poll.py LongPollHost snapshot ids).
        self._version_cv = threading.Condition(self._lock)
        self._stopping = False
        self._scaler = threading.Thread(
            target=self._scale_loop, daemon=True, name="serve-scaler")
        self._scaler.start()
        # Drain plane: replicas on a DRAINING node (announced TPU
        # preemption / maintenance event) are replaced proactively —
        # a new replica passes readiness elsewhere, then the doomed one
        # drains its in-flight work via _drain_then_kill.
        self._drainer = threading.Thread(
            target=self._node_drain_loop, daemon=True,
            name="serve-drain-watch")
        self._drainer.start()

    def _bump_version_locked(self, entry: dict) -> None:
        entry["version"] = entry.get("version", 0) + 1
        self._version_cv.notify_all()

    def listen_for_change(self, keys: dict, timeout_s: float = 30.0):
        """Block until any listed deployment's version passes the
        caller's, then return the changed routing infos; {} on timeout
        (the caller re-arms).  A deleted deployment reports None."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while True:
                changed: dict = {}
                for name, known in keys.items():
                    entry = self._deployments.get(name)
                    if entry is None:
                        changed[name] = None
                    elif entry.get("version", 0) > known:
                        changed[name] = {
                            "version": entry["version"],
                            "replicas": list(entry["replicas"]),
                            "ongoing": list(entry["ongoing"]),
                            "config":
                                entry["deployment"].overload_config(),
                            "suspect": set(entry.get("suspect", ()))}
                if changed:
                    return changed
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {}
                self._version_cv.wait(remaining)

    def _make_replicas(self, deployment: Deployment, args, kwargs, n: int,
                       timeout: float | None = None):
        art = _art()
        # Default is SERIALIZED user code (max_concurrency=1, matching
        # plain actors).  Autoscaling needs overlapping requests for a
        # meaningful queue-depth signal, so it defaults to 8 — like the
        # reference's max_ongoing_requests > 1, replica code must then
        # be thread-safe.  @serve.batch also requires an explicit
        # max_concurrency.
        default_conc = 8 if deployment.autoscaling_config is not None else 1
        if deployment.max_ongoing_requests is not None:
            # Admission control moves the execution bound into the
            # replica's gate (max_ongoing slots + max_queued waiters),
            # so the actor's thread pool must be WIDER than the gate:
            # excess calls need a thread to reach the gate and
            # fast-fail, and ongoing()/health() polls must not starve
            # behind queued work (+8 headroom for both).
            default_conc = (deployment.max_ongoing_requests
                            + max(deployment.max_queued_requests, 0) + 8)
        replica_cls = art.remote(Replica).options(
            **{"num_cpus": deployment.ray_actor_options.get("num_cpus", 0),
               "max_concurrency": deployment.ray_actor_options.get(
                   "max_concurrency", default_conc)})
        limits = {"deployment": deployment.name,
                  "max_ongoing_requests": deployment.max_ongoing_requests,
                  "max_queued_requests": deployment.max_queued_requests}
        replicas = [
            replica_cls.remote(deployment.cls_or_fn, args, kwargs, limits)
            for _ in range(n)
        ]
        try:
            # Readiness gate.  ``timeout`` lets retry-loop callers (the
            # drain watcher) bound an unplaceable replica instead of
            # wedging their thread forever.
            art.get([r.health.remote() for r in replicas],
                    timeout=timeout)
        except BaseException:
            # Never leak half-placed replicas: handles aren't reaped on
            # GC, and a retrying caller would compound the leak — worse,
            # the leaked actors hold exactly the capacity the retry
            # needs, guaranteeing it never succeeds.
            for r in replicas:
                try:
                    art.kill(r)
                except Exception:  # noqa: BLE001
                    pass
            raise
        return replicas

    def deploy(self, deployment: Deployment, args, kwargs) -> dict:
        if self._deployments.get(deployment.name) is not None:
            return self._rolling_redeploy(deployment, args, kwargs)
        return self._fresh_deploy(deployment, args, kwargs)

    def _fresh_deploy(self, deployment: Deployment, args, kwargs) -> dict:
        n = deployment.num_replicas
        if deployment.autoscaling_config is not None:
            n = deployment.autoscaling_config.min_replicas
        replicas = self._make_replicas(deployment, args, kwargs, n)
        with self._lock:
            entry = {
                "deployment": deployment,
                "args": args,
                "kwargs": kwargs,
                "replicas": replicas,
                "route_prefix": deployment.route_prefix,
                "ongoing": [0] * len(replicas),
                "low_streak": 0,
                "version": 0,
                # Per-replica consecutive ongoing-poll failures; at
                # _POLL_STRIKE_LIMIT the replica is marked suspect and
                # every handle's breaker force-opens against it.
                "strikes": {},
                "suspect": set(),
            }
            self._deployments[deployment.name] = entry
            self._bump_version_locked(entry)
        return {"name": deployment.name}

    def _rolling_redeploy(self, deployment: Deployment, args,
                          kwargs) -> dict:
        """Replace an existing deployment's replicas version-by-version
        with at most ``rolling_max_surge`` extra replicas alive at a
        time (ref: deployment_state.py:2597 rolling updates).  Each new
        replica passes its readiness gate BEFORE a predecessor starts
        draining, so the serving count never dips below target and no
        request is dropped: handles learn each swap via the long-poll
        version push while the replaced replica drains in-flight work
        on the old code before dying."""
        art = _art()
        name = deployment.name
        with self._lock:
            entry = self._deployments.get(name)
            raced_delete = entry is None
            if not raced_delete:
                entry["deployment"] = deployment
                entry["args"] = args
                entry["kwargs"] = kwargs
                entry["route_prefix"] = deployment.route_prefix
                remaining = collections.deque(entry["replicas"])
        if raced_delete:
            # The deployment vanished between deploy()'s existence check
            # and here: the caller asked for this app to be RUNNING, so
            # deploy fresh rather than returning success with nothing
            # deployed.
            return self._fresh_deploy(deployment, args, kwargs)
        surge = max(1, deployment.rolling_max_surge)
        while remaining:
            doomed = [remaining.popleft()
                      for _ in range(min(surge, len(remaining)))]
            fresh = self._make_replicas(deployment, args, kwargs,
                                        len(doomed))
            swapped = []
            with self._lock:
                entry = self._deployments.get(name)
                if entry is None:          # deleted mid-roll
                    for r in fresh:
                        try:
                            art.kill(r)
                        except Exception:  # noqa: BLE001
                            pass
                    return {"name": name}
                for old_r, new_r in zip(doomed, fresh):
                    try:
                        idx = entry["replicas"].index(old_r)
                    except ValueError:     # autoscaler removed it mid-roll
                        entry["replicas"].append(new_r)
                        entry["ongoing"].append(0)
                        continue
                    entry["replicas"][idx] = new_r
                    entry["ongoing"][idx] = 0
                    swapped.append(old_r)
                self._bump_version_locked(entry)
            for replica in swapped:
                threading.Thread(target=self._drain_then_kill,
                                 args=(replica,), daemon=True).start()
        # Converge to the new target size (autoscaling keeps its current
        # count clamped to the new bounds; fixed deployments resize).
        with self._lock:
            entry = self._deployments.get(name)
            current = len(entry["replicas"]) if entry else 0
        if entry is not None:
            cfg = deployment.autoscaling_config
            target = (max(cfg.min_replicas,
                          min(current, cfg.max_replicas)) if cfg
                      else deployment.num_replicas)
            if target > current:
                self._scale_up(name, target - current)
            elif target < current:
                self._scale_down(name, current - target)
        return {"name": name}

    def get_handle_info(self, name: str):
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return None
            return {"replicas": list(entry["replicas"]),
                    "ongoing": list(entry["ongoing"]),
                    "version": entry.get("version", 0),
                    "config": entry["deployment"].overload_config(),
                    "suspect": set(entry.get("suspect", ()))}

    # ------------------------------------------------------ autoscaling

    def _poll_ongoing_all(self, entries: list) -> dict:
        """Issue EVERY deployment's per-replica ``ongoing()`` polls up
        front and bound them with ONE combined wait: a wedged replica
        costs _POLL_TIMEOUT_S once per loop iteration, not once per
        deployment, so strike cadence (and healthy deployments' queue
        snapshots) never degrade with deployment count."""
        art = _art()
        polls = [(name, replicas,
                  [r.ongoing.remote() for r in replicas])
                 for name, replicas in entries]
        all_refs = [ref for _, _, refs in polls for ref in refs]
        if not all_refs:
            return {}
        try:
            art.wait(all_refs, num_returns=len(all_refs),
                     timeout=_POLL_TIMEOUT_S)
        except Exception:  # noqa: BLE001 — control plane blip
            return {}
        out = {}
        for name, replicas, refs in polls:
            counts = self._collect_ongoing(name, replicas, refs)
            if counts is not None:
                out[name] = counts
        return out

    def _collect_ongoing(self, name: str, replicas: list,
                         refs: list) -> "list | None":
        """Per-replica queue-depth poll with STRIKE accounting.  The old
        loop did one batched ``art.get`` and swallowed every exception —
        a single wedged replica froze the whole deployment's queue
        snapshot at its last value, and po2 kept routing to the wedge
        forever.  Now each replica answers (or fails) individually:
        consecutive failures count strikes, and at _POLL_STRIKE_LIMIT
        the replica is marked SUSPECT — pushed to every handle, whose
        breaker force-opens against it until a later poll succeeds."""
        art = _art()
        counts: list = [None] * len(replicas)
        failed: list = []
        for i, (replica, ref) in enumerate(zip(replicas, refs)):
            try:
                counts[i] = int(art.get(ref, timeout=0))
            except Exception:  # noqa: BLE001 — timeout, died, wedged
                failed.append(replica.actor_id)
        suspect_changed = False
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None or entry["replicas"] != replicas:
                return None
            strikes = entry["strikes"]
            suspect = entry["suspect"]
            live = {r.actor_id for r in replicas}
            for aid in list(strikes):
                if aid not in live:
                    strikes.pop(aid)
            suspect_stale = suspect - live
            for i, replica in enumerate(replicas):
                aid = replica.actor_id
                if counts[i] is None:
                    strikes[aid] = strikes.get(aid, 0) + 1
                    if (strikes[aid] >= _POLL_STRIKE_LIMIT
                            and aid not in suspect):
                        suspect.add(aid)
                        suspect_changed = True
                    # Keep the last known depth for the snapshot; the
                    # breaker (not a stale low count) removes a suspect
                    # replica from routing.
                    counts[i] = (entry["ongoing"][i]
                                 if i < len(entry["ongoing"]) else 0)
                else:
                    strikes.pop(aid, None)
                    if aid in suspect:
                        suspect.discard(aid)
                        suspect_changed = True
            if suspect_stale:
                suspect -= suspect_stale
                suspect_changed = True
            entry["ongoing"] = counts
            if suspect_changed:
                # Suspect verdicts ride the same long-poll push as
                # replica-set changes: every handle hears within one
                # listen round trip.
                self._bump_version_locked(entry)
            n_suspect = len(suspect)
        _emit("queue_depth", sum(counts), {"deployment": name})
        _emit("suspect", n_suspect, {"deployment": name})
        return counts

    def _poll_signal_total(self, replicas: list,
                           signal: str) -> "float | None":
        """Sum one named load signal across a deployment's replicas
        (signal-targeted autoscaling).  A replica that fails to answer
        contributes 0; None only when EVERY poll failed (no basis for a
        decision — the ongoing-based desired stands alone)."""
        art = _art()
        refs = [r.load_signals.remote() for r in replicas]
        try:
            art.wait(refs, num_returns=len(refs),
                     timeout=_POLL_TIMEOUT_S)
        except Exception:  # noqa: BLE001 — control plane blip
            return None
        total, answered = 0.0, 0
        for ref in refs:
            try:
                signals = art.get(ref, timeout=0)
                answered += 1
                total += float(signals.get(signal, 0.0))
            except Exception:  # noqa: BLE001 — wedged replica
                continue
        return total if answered else None

    def _scale_loop(self):
        while not self._stopping:
            time.sleep(0.25)
            with self._lock:
                snapshot = [(name, list(entry["replicas"]),
                             entry["deployment"].autoscaling_config)
                            for name, entry in self._deployments.items()]
            polled = self._poll_ongoing_all(
                [(name, replicas) for name, replicas, _ in snapshot])
            for name, replicas, cfg in snapshot:
                counts = polled.get(name)
                if counts is None:
                    continue
                if cfg is None:
                    continue
                with self._lock:
                    entry = self._deployments.get(name)
                    if entry is None:
                        continue
                    # Queue depths refresh every poll; scaling DECISIONS
                    # honour the config's cadence.
                    last = entry.get("last_decision", 0.0)
                    if time.monotonic() - last < cfg.interval_s:
                        continue
                    entry["last_decision"] = time.monotonic()
                desired = math.ceil(
                    sum(counts) / max(cfg.target_ongoing_requests, 1e-9))
                if cfg.target_signal:
                    total = self._poll_signal_total(
                        replicas, cfg.target_signal)
                    if total is not None:
                        _emit(cfg.target_signal, total,
                              {"deployment": name})
                        desired = max(desired, math.ceil(
                            total / max(cfg.target_value, 1e-9)))
                desired = max(cfg.min_replicas,
                              min(cfg.max_replicas, desired))
                if desired > len(replicas):
                    self._scale_up(name, desired - len(replicas))
                elif desired < len(replicas):
                    with self._lock:
                        entry = self._deployments.get(name)
                        if entry is None:
                            continue
                        entry["low_streak"] += 1
                        trigger = entry["low_streak"] >= \
                            cfg.downscale_patience
                    if trigger:
                        self._scale_down(name, len(replicas) - desired)
                else:
                    with self._lock:
                        entry = self._deployments.get(name)
                        if entry is not None:
                            entry["low_streak"] = 0

    def _scale_up(self, name: str, count: int):
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return
            deployment, args, kwargs = (entry["deployment"],
                                        entry["args"], entry["kwargs"])
        try:
            new = self._make_replicas(deployment, args, kwargs, count)
        except Exception:  # noqa: BLE001 — cluster may lack resources
            return
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return
            entry["replicas"] = entry["replicas"] + new
            entry["ongoing"] = entry["ongoing"] + [0] * len(new)
            entry["low_streak"] = 0
            self._bump_version_locked(entry)

    def _scale_down(self, name: str, count: int):
        doomed = []
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return
            # Prefer idle replicas, scanning from the tail.
            for index in reversed(range(len(entry["replicas"]))):
                if len(doomed) == count:
                    break
                if entry["ongoing"][index] == 0:
                    doomed.append(entry["replicas"].pop(index))
                    entry["ongoing"].pop(index)
            entry["low_streak"] = 0
            if doomed:
                self._bump_version_locked(entry)
        for replica in doomed:
            # Drain before killing: client handles cache the replica set
            # for up to the refresh TTL, so an immediate kill would turn
            # in-flight/imminent requests into ActorDiedErrors.
            threading.Thread(target=self._drain_then_kill,
                             args=(replica,), daemon=True).start()

    # -------------------------------------------------- node drain plane

    def _node_drain_loop(self):
        """Watch for DRAINING nodes (announced preemption/maintenance)
        and migrate their replicas: spin up replacements — the
        scheduler already skips draining nodes — and hand the doomed
        replicas to the existing ``_drain_then_kill`` machinery so
        in-flight requests finish before the node dies."""
        art = _art()
        while not self._stopping:
            time.sleep(1.0)
            try:
                draining = {n["NodeID"] for n in art.nodes()
                            if n["Alive"] and n.get("Draining")}
                if not draining:
                    continue
                from ant_ray_tpu.api import global_worker  # noqa: PLC0415

                on_node = {rec["actor_id"]: rec.get("node_id")
                           for rec in global_worker.runtime._gcs.call(
                               "ListActors", retries=3)
                           if rec.get("state") != "DEAD"}
            except Exception:  # noqa: BLE001 — control plane blip
                continue
            with self._lock:
                names = list(self._deployments)
            for name in names:
                try:
                    self._migrate_off_draining(name, draining, on_node)
                except Exception:  # noqa: BLE001 — retried next tick
                    pass

    def _migrate_off_draining(self, name: str, draining: set,
                              on_node: dict) -> None:
        art = _art()
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:
                return
            doomed = [r for r in entry["replicas"]
                      if on_node.get(r.actor_id.hex()) in draining]
            deployment, args, kwargs = (entry["deployment"],
                                        entry["args"], entry["kwargs"])
        if not doomed:
            return
        # Replacements pass their readiness gate BEFORE any doomed
        # replica starts draining — the serving count never dips (the
        # same no-dip invariant as _rolling_redeploy).
        fresh = self._make_replicas(deployment, args, kwargs, len(doomed),
                                    timeout=60.0)
        swapped = []
        with self._lock:
            entry = self._deployments.get(name)
            if entry is None:              # deleted mid-migration
                for r in fresh:
                    try:
                        art.kill(r)
                    except Exception:  # noqa: BLE001
                        pass
                return
            for old_r, new_r in zip(doomed, fresh):
                try:
                    idx = entry["replicas"].index(old_r)
                except ValueError:   # autoscaler removed it meanwhile
                    entry["replicas"].append(new_r)
                    entry["ongoing"].append(0)
                    continue
                entry["replicas"][idx] = new_r
                entry["ongoing"][idx] = 0
                swapped.append(old_r)
            self._bump_version_locked(entry)
        for replica in swapped:
            threading.Thread(target=self._drain_then_kill,
                             args=(replica,), daemon=True).start()

    def _drain_then_kill(self, replica):
        art = _art()
        # Handles learn about the shrink via the long-poll push within
        # one round trip; a short grace covers requests already routed
        # and listeners between poll windows.
        time.sleep(2.0)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                if art.get(replica.ongoing.remote(), timeout=5) == 0:
                    break
            except Exception:  # noqa: BLE001 — already gone
                break
            time.sleep(0.5)
        try:
            art.kill(replica)
        except Exception:  # noqa: BLE001
            pass
        _expire_replica_series(replica)

    @staticmethod
    def _expire_deployment_series(name: str) -> None:
        """Drop a removed deployment's ``art_serve_*`` series from the
        GCS metrics table (queue depth, shed counters, suspect gauges
        would otherwise report a deleted deployment forever)."""
        try:
            from ant_ray_tpu.api import global_worker  # noqa: PLC0415

            rt = global_worker.runtime
            rt._send_oneway(rt.gcs_address, "MetricsExpire",
                            {"match_tags": {"deployment": name},
                             "name_prefix": "art_serve_"})
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass

    def list_deployments(self):
        return {
            name: {
                "num_replicas": len(e["replicas"]),
                "route_prefix": e["route_prefix"],
            }
            for name, e in self._deployments.items()
        }

    def routes(self):
        return {
            e["route_prefix"]: name
            for name, e in self._deployments.items()
            if e["route_prefix"]
        }

    def start_grpc_proxy(self, port: int) -> int:
        art = _art()
        if getattr(self, "_grpc_proxy", None) is None:
            proxy_cls = art.remote(GrpcProxy).options(
                max_concurrency=32, num_cpus=0)
            controller = art.get_actor(CONTROLLER_NAME,
                                       namespace="_serve")
            self._grpc_proxy = proxy_cls.remote(controller)
        return art.get(self._grpc_proxy.start.remote(port))

    def start_http_proxy(self, port: int) -> int:
        art = _art()
        if self._proxy is None:
            proxy_cls = art.remote(HttpProxy).options(
                max_concurrency=32, num_cpus=0)
            controller = art.get_actor(CONTROLLER_NAME,
                                       namespace="_serve")
            self._proxy = proxy_cls.remote(controller)
        return art.get(self._proxy.start.remote(port))

    def shutdown_all(self):
        art = _art()
        # Stop the background scaler/drain watchers first: a watcher
        # migrating replicas mid-shutdown would resurrect actors the
        # loop below is killing.
        self._stopping = True
        # Snapshot + clear UNDER the lock: an in-flight drain migration
        # swaps its fresh replicas into the entry under this same lock,
        # so they land either in the snapshot (killed below) or after
        # the clear (its deleted-entry branch kills them) — never in a
        # leaked gap between an unlocked kill loop and the clear.
        with self._lock:
            doomed = [r for entry in self._deployments.values()
                      for r in entry["replicas"]]
            names = list(self._deployments)
            self._deployments.clear()
            # Wake parked listeners: their deployments now read as
            # deleted, so listener threads exit instead of waiting out
            # the poll window against a dead controller.
            self._version_cv.notify_all()
        for r in doomed:
            try:
                art.kill(r)
            except Exception:  # noqa: BLE001
                pass
            _expire_replica_series(r)
        for name in names:
            self._expire_deployment_series(name)
        for proxy in (self._proxy, getattr(self, "_grpc_proxy", None)):
            if proxy is not None:
                try:
                    art.kill(proxy)
                except Exception:  # noqa: BLE001
                    pass
        self._deployments.clear()
        return True


class HttpProxy:
    """aiohttp ingress routing requests to deployments by route prefix
    (ref: serve/_private/proxy.py)."""

    def __init__(self, controller):
        self._controller = controller
        self._port = None
        self._runner = None
        # name -> DeploymentHandle: handles are long-lived (each owns a
        # routing state kept fresh by its long-poll listener), so the
        # proxy reuses one per deployment instead of re-resolving every
        # request.
        self._handles: dict[str, DeploymentHandle] = {}
        self._handles_lock = threading.Lock()

    def start(self, port: int) -> int:
        import asyncio  # noqa: PLC0415
        import threading  # noqa: PLC0415

        from aiohttp import web  # noqa: PLC0415

        art = _art()
        loop = asyncio.new_event_loop()

        def resolve_handle(path: str) -> "DeploymentHandle | None":
            routes = art.get(self._controller.routes.remote())
            for prefix, name in routes.items():
                if path.startswith(prefix):
                    with self._handles_lock:
                        handle = self._handles.get(name)
                        if handle is None:
                            info = art.get(
                                self._controller.get_handle_info.remote(
                                    name))
                            handle = DeploymentHandle(
                                name, info["replicas"],
                                controller=self._controller,
                                _info=info)
                            self._handles[name] = handle
                    return handle
            return None

        def shed_response(e: BaseException):
            """Typed overload errors → the documented HTTP statuses:
            429 + Retry-After (seconds, integral and >= 1 per RFC 9110)
            for sheds, 504 for deadline misses; None for anything else.
            The ONE place the HTTP shed contract is rendered — unary
            and streaming both route through it."""
            typed = _typed_cause(e)
            if isinstance(typed, BackPressureError):
                return web.json_response(
                    {"error": str(typed),
                     "retry_after_s": typed.retry_after_s},
                    status=429,
                    headers={"Retry-After": str(
                        max(1, math.ceil(typed.retry_after_s)))})
            if isinstance(typed, DeadlineExceededError):
                return web.json_response({"error": str(typed)},
                                         status=504)
            return None

        def dispatch(path: str, body, timeout_s: float | None):
            """Blocking route+call (runs on an executor thread so the
            aiohttp loop stays free; building an unprepared Response
            off-loop is fine).  Routes through ``handle.call`` for the
            full overload contract.

            Tracing ingress: a root context is minted per request and
            scoped over the call; the ``http:{path}`` span records the
            end-to-end server time, force-sampled with ``error:true``
            when the request sheds (429) or misses its deadline (504)."""
            handle = resolve_handle(path)
            if handle is None:
                return web.json_response(
                    {"error": f"no route for {path}"}, status=404)
            if isinstance(body, dict):
                # Deployments that serve several REST endpoints under
                # one prefix (e.g. /v1/completions + /v1/chat/...)
                # dispatch on the request path (ref: proxy passes the
                # scope through to the replica).
                body.setdefault("__route_path__", path)
            ctx = tracing_plane.mint()
            t_wall = time.time()
            t0 = time.perf_counter()
            status = 200
            try:
                with tracing_plane.use(ctx):
                    return web.json_response(
                        {"result": handle.call(body,
                                               timeout_s=timeout_s)})
            except Exception as e:  # noqa: BLE001 — classified below
                resp = shed_response(e)
                if resp is not None:
                    status = resp.status
                    return resp
                status = 500
                return web.json_response({"error": repr(e)}, status=500)
            finally:
                tracing_plane.record_span(
                    ctx, f"http:{path}", ts=t_wall,
                    dur_s=time.perf_counter() - t0,
                    attrs={"path": path, "status": status},
                    error=status >= 400, span_id=ctx.span_id,
                    parent_id="", service="http-proxy")

        def stream_start(path: str, body, timeout_s: float | None):
            """Start a streaming call; returns (handle, replica,
            ObjectRefGenerator) — the replica so the caller can feed
            the stream's outcome into its breaker (convention:
            ``{"stream": true}`` requests dispatch to the deployment's
            ``stream`` method as a generator).  The end-to-end deadline
            (explicit header or deployment default) is stamped on the
            dispatch like the unary path."""
            handle = resolve_handle(path)
            if handle is None:
                return None
            if isinstance(body, dict):
                body.setdefault("__route_path__", path)
            h = handle.options(method_name="stream", stream=True)
            h._maybe_refresh()
            # Streaming ingress mints the trace root too; the span is
            # recorded when dispatch fails (shed) — mid-stream life is
            # covered by the replica-side stream span.
            ctx = tracing_plane.mint()
            t_wall = time.time()
            t0 = time.perf_counter()
            try:
                with tracing_plane.use(ctx):
                    replica = h._pick()  # may raise typed BackPressure
                    gen = h._dispatch(replica, (body,), {},
                                      h._mux_model_id,
                                      h._request_meta(timeout_s,
                                                      trace=ctx))
            except BaseException:
                tracing_plane.record_span(
                    ctx, f"http:{path}", ts=t_wall,
                    dur_s=time.perf_counter() - t0,
                    attrs={"path": path, "stream": True}, error=True,
                    span_id=ctx.span_id, parent_id="",
                    service="http-proxy")
                raise
            return (h, replica, gen)

        def next_chunk(gen):
            try:
                ref = next(gen)
            except StopIteration:
                return None
            return art.get(ref)

        async def handler(request: "web.Request"):
            import json as _json  # noqa: PLC0415

            try:
                body = await request.json() if request.can_read_body else {}
            except Exception:  # noqa: BLE001
                body = {}
            loop_ = asyncio.get_running_loop()
            # Client-requested end-to-end deadline: seconds from now in
            # the X-Request-Timeout-S header (wins over the
            # deployment's request_timeout_s default).  Parsed before
            # the stream branch — streaming requests carry deadlines
            # too.
            timeout_s = None
            raw_timeout = request.headers.get("X-Request-Timeout-S")
            if raw_timeout:
                try:
                    timeout_s = float(raw_timeout)
                except ValueError:
                    return web.json_response(
                        {"error": "X-Request-Timeout-S must be a "
                                  "float (seconds)"}, status=400)
            if isinstance(body, dict) and body.get("stream"):
                # Server-sent events: one `data:` frame per produced
                # chunk, flowing while the model still generates
                # (ref: serve streaming HTTP responses).
                try:
                    started = await loop_.run_in_executor(
                        None, stream_start, request.path, body,
                        timeout_s)
                except Exception as e:  # noqa: BLE001 — classified below
                    # _pick with every replica ejected raises typed
                    # BackPressureError: same shed contract as unary.
                    # NB: explicit None check — an unprepared
                    # web.Response is FALSY (it has __len__), so `or`
                    # would silently discard the 429.
                    resp_t = shed_response(e)
                    if resp_t is not None:
                        return resp_t
                    return web.json_response({"error": repr(e)},
                                             status=500)
                if started is None:
                    return web.json_response(
                        {"error": f"no route for {request.path}"},
                        status=404)
                sh, replica, gen = started
                # Pull the FIRST chunk before sending SSE headers: the
                # replica's admission gate / deadline check fires on
                # generator start, so a shed must surface as the
                # documented typed status — not a 200 that dies
                # mid-stream with no Retry-After.
                try:
                    chunk = await loop_.run_in_executor(
                        None, next_chunk, gen)
                except Exception as e:  # noqa: BLE001 — classified below
                    _record_result(sh._routing, replica, e)
                    resp_t = shed_response(e)
                    if resp_t is not None:
                        return resp_t
                    return web.json_response({"error": repr(e)},
                                             status=500)
                resp = web.StreamResponse(
                    headers={"Content-Type": "text/event-stream",
                             "Cache-Control": "no-cache"})
                await resp.prepare(request)
                while chunk is not None:
                    await resp.write(
                        b"data: " + _json.dumps(chunk).encode() + b"\n\n")
                    try:
                        chunk = await loop_.run_in_executor(
                            None, next_chunk, gen)
                    except Exception as e:  # noqa: BLE001 — mid-stream
                        # Headers already went out: feed the breaker
                        # and end the stream (the client sees the
                        # missing [DONE]).  resp.write failures (client
                        # gone) are NOT replica outcomes and propagate.
                        _record_result(sh._routing, replica, e)
                        await resp.write_eof()
                        return resp
                _record_result(sh._routing, replica)
                await resp.write(b"data: [DONE]\n\n")
                await resp.write_eof()
                return resp
            return await loop_.run_in_executor(
                None, dispatch, request.path, body, timeout_s)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        started = threading.Event()
        port_holder = {}

        def _serve():
            asyncio.set_event_loop(loop)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", port)
            loop.run_until_complete(site.start())
            port_holder["port"] = site._server.sockets[0].getsockname()[1]
            self._runner = runner
            started.set()
            loop.run_forever()

        threading.Thread(target=_serve, daemon=True).start()
        started.wait(10)
        self._port = port_holder.get("port")
        return self._port


class GrpcProxy:
    """gRPC ingress alongside HTTP (ref: serve/_private/proxy.py:533
    ``class gRPCProxy``).

    Redesigned without per-user proto codegen: ONE generic service,
    ``antray.serve.Ingress``, speaks JSON-over-gRPC —

      rpc Call(bytes)   returns (bytes)          # unary
      rpc Stream(bytes) returns (stream bytes)   # server streaming

    Request bytes are UTF-8 JSON ``{"route": "/prefix/...", "request":
    {...}}``; the reply is the deployment's JSON response.  Clients
    need only ``grpc.Channel.unary_unary`` with identity serializers —
    no generated stubs."""

    def __init__(self, controller):
        self._controller = controller
        self._server = None
        self._handles: dict[str, DeploymentHandle] = {}
        self._handles_lock = threading.Lock()

    def _resolve_handle(self, path: str) -> "DeploymentHandle | None":
        art = _art()
        routes = art.get(self._controller.routes.remote())
        for prefix, name in routes.items():
            if path.startswith(prefix):
                with self._handles_lock:
                    handle = self._handles.get(name)
                    if handle is None:
                        info = art.get(
                            self._controller.get_handle_info.remote(name))
                        handle = DeploymentHandle(
                            name, info["replicas"],
                            controller=self._controller, _info=info)
                        self._handles[name] = handle
                return handle
        return None

    @staticmethod
    def _parse(request_bytes, context):
        import json  # noqa: PLC0415

        import grpc  # noqa: PLC0415

        try:
            payload = json.loads(request_bytes.decode("utf-8"))
            route = payload["route"]
        except Exception:  # noqa: BLE001
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          'want JSON {"route": ..., "request": {...}}')
        body = payload.get("request", {})
        if isinstance(body, dict):
            body.setdefault("__route_path__", route)
        return route, body

    @staticmethod
    def _abort_overload(context, e: BaseException) -> None:
        """abort() with the documented typed mapping — the ONE place
        the gRPC shed contract is rendered (RESOURCE_EXHAUSTED + the
        retry hint in a ``retry-after-s`` trailer / DEADLINE_EXCEEDED).
        Returns (without aborting) when ``e`` is not an overload error;
        the caller handles it."""
        import grpc  # noqa: PLC0415

        typed = _typed_cause(e)
        if isinstance(typed, BackPressureError):
            context.set_trailing_metadata(
                (("retry-after-s", f"{typed.retry_after_s:.3f}"),))
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(typed))
        if isinstance(typed, DeadlineExceededError):
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(typed))

    def _call(self, request_bytes, context):
        import json  # noqa: PLC0415

        import grpc  # noqa: PLC0415

        route, body = self._parse(request_bytes, context)
        handle = self._resolve_handle(route)
        if handle is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no route for {route}")
        # End-to-end deadline: the native gRPC deadline (time_remaining)
        # and/or an explicit {"timeout_s": ...} in the payload — the
        # tighter one wins; the deployment default applies when neither
        # is set.
        timeout_s = None
        if isinstance(body, dict) and body.get("timeout_s") is not None:
            try:
                timeout_s = float(body["timeout_s"])
            except (TypeError, ValueError):
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "timeout_s must be a number (seconds)")
        native = context.time_remaining()
        if native is not None:
            timeout_s = (native if timeout_s is None
                         else min(timeout_s, native))
        # Tracing ingress (gRPC unary): mint, scope, record — sheds
        # force-sample an error span carrying the trace id the client
        # can quote from the trailer-documented retry contract.
        ctx = tracing_plane.mint()
        t_wall = time.time()
        t0 = time.perf_counter()
        ok = False
        try:
            with tracing_plane.use(ctx):
                result = handle.call(body, timeout_s=timeout_s)
            ok = True
        except Exception as e:  # noqa: BLE001 — classified below
            self._abort_overload(context, e)
            context.abort(grpc.StatusCode.INTERNAL, repr(e))
        finally:
            tracing_plane.record_span(
                ctx, f"grpc:{route}", ts=t_wall,
                dur_s=time.perf_counter() - t0,
                attrs={"route": route}, error=not ok,
                span_id=ctx.span_id, parent_id="",
                service="grpc-proxy")
        return json.dumps({"result": result}).encode("utf-8")

    def _stream(self, request_bytes, context):
        import json  # noqa: PLC0415

        import grpc  # noqa: PLC0415

        art = _art()
        route, body = self._parse(request_bytes, context)
        handle = self._resolve_handle(route)
        if handle is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no route for {route}")
        # Deadline rides the native gRPC call deadline; sheds map to
        # the same typed statuses as the unary path (the replica's
        # admission gate fires on generator start, i.e. the first get).
        h = handle.options(method_name="stream", stream=True)
        h._maybe_refresh()
        ctx = tracing_plane.mint()
        with tracing_plane.use(ctx):
            try:
                replica = h._pick()
            except BackPressureError as e:
                tracing_plane.record_span(
                    ctx, f"grpc:{route}", ts=time.time(), dur_s=0.0,
                    attrs={"route": route, "stream": True}, error=True,
                    span_id=ctx.span_id, parent_id="",
                    service="grpc-proxy")
                # Every replica ejected: same shed contract as unary.
                self._abort_overload(context, e)
            gen = h._dispatch(replica, (body,), {}, h._mux_model_id,
                              h._request_meta(context.time_remaining(),
                                              trace=ctx))
        try:
            for ref in gen:
                yield json.dumps(art.get(ref)).encode("utf-8")
        except Exception as e:  # noqa: BLE001 — classified below
            _record_result(h._routing, replica, e)
            self._abort_overload(context, e)
            raise
        _record_result(h._routing, replica)

    def start(self, port: int) -> int:
        from concurrent import futures  # noqa: PLC0415

        import grpc  # noqa: PLC0415

        proxy = self

        class _Ingress(grpc.GenericRpcHandler):
            def service(self, details):
                if details.method == "/antray.serve.Ingress/Call":
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._call)
                if details.method == "/antray.serve.Ingress/Stream":
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._stream)
                return None

        server = grpc.server(futures.ThreadPoolExecutor(max_workers=16))
        server.add_generic_rpc_handlers((_Ingress(),))
        bound = server.add_insecure_port(f"127.0.0.1:{port}")
        server.start()
        self._server = server
        return bound


# ---------------------------------------------------------------- run api

def _get_or_create_controller():
    art = _art()
    try:
        return art.get_actor(CONTROLLER_NAME, namespace="_serve")
    except ValueError:
        # Generous concurrency: each handle family parks one blocking
        # listen_for_change call here (ref: LongPollHost runs on the
        # controller event loop; this threaded controller needs slots).
        controller_cls = art.remote(ServeController).options(
            name=CONTROLLER_NAME, namespace="_serve", get_if_exists=True,
            max_concurrency=64, num_cpus=0, lifetime="detached")
        return controller_cls.remote()


def run(app: Application, *, port: int | None = None,
        grpc_port: int | None = None) -> DeploymentHandle:
    """Deploy an application; returns its handle (ref: serve.run).
    ``grpc_port`` additionally starts the gRPC ingress (0 = ephemeral;
    bound port in ``run.last_grpc_port``)."""
    art = _art()
    if not art.is_initialized():
        art.init()
    controller = _get_or_create_controller()
    art.get(controller.deploy.remote(app.deployment, app.args, app.kwargs))
    if port is not None or app.deployment.route_prefix:
        actual = art.get(controller.start_http_proxy.remote(
            8000 if port is None else port))
        run.last_http_port = actual  # discoverable for tests/clients
    if grpc_port is not None:
        run.last_grpc_port = art.get(
            controller.start_grpc_proxy.remote(grpc_port))
    info = art.get(
        controller.get_handle_info.remote(app.deployment.name))
    # The controller reference lets the handle refresh its replica set
    # (autoscaling) and queue snapshot (po2 routing) on a TTL.
    return DeploymentHandle(app.deployment.name, info["replicas"],
                            controller=controller, _info=info)


run.last_http_port = None
run.last_grpc_port = None


def shutdown():
    art = _art()
    try:
        controller = art.get_actor(CONTROLLER_NAME, namespace="_serve")
    except ValueError:
        return
    try:
        art.get(controller.shutdown_all.remote())
        art.kill(controller)
    except Exception:  # noqa: BLE001
        pass
