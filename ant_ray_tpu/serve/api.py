"""Serve: deployments, replicas, routing, HTTP ingress.

Scaled-down mirror of the reference architecture (SURVEY §2.4 Serve /
§3.6): ``serve.run`` starts a named **controller actor** that reconciles
desired deployment state into **replica actors**; **handles** route calls
to replicas (round-robin with pending-count preference — the seed of
power-of-two-choices, ref: serve/_private/router.py:472); an optional
aiohttp **proxy actor** exposes deployments over HTTP
(ref: serve/_private/proxy.py).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable

CONTROLLER_NAME = "_serve_controller"


def _art():
    import ant_ray_tpu as art  # noqa: PLC0415

    return art


# ---------------------------------------------------------------- public

@dataclass
class Deployment:
    cls_or_fn: Any
    name: str
    num_replicas: int = 1
    route_prefix: str | None = None
    ray_actor_options: dict = field(default_factory=dict)
    init_args: tuple = ()
    init_kwargs: dict = field(default_factory=dict)

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def options(self, *, num_replicas: int | None = None,
                route_prefix: str | None = None,
                name: str | None = None) -> "Deployment":
        return Deployment(
            cls_or_fn=self.cls_or_fn,
            name=name or self.name,
            num_replicas=num_replicas or self.num_replicas,
            route_prefix=(route_prefix if route_prefix is not None
                          else self.route_prefix),
            ray_actor_options=dict(self.ray_actor_options),
            init_args=self.init_args,
            init_kwargs=dict(self.init_kwargs),
        )


@dataclass
class Application:
    deployment: Deployment
    args: tuple
    kwargs: dict


def deployment(_cls=None, *, name: str | None = None, num_replicas: int = 1,
               route_prefix: str | None = None,
               ray_actor_options: dict | None = None):
    """``@serve.deployment`` decorator (ref: serve/api.py)."""

    def wrap(cls_or_fn):
        return Deployment(
            cls_or_fn=cls_or_fn,
            name=name or getattr(cls_or_fn, "__name__", "deployment"),
            num_replicas=num_replicas,
            route_prefix=route_prefix,
            ray_actor_options=dict(ray_actor_options or {}),
        )

    if _cls is not None:
        return wrap(_cls)
    return wrap


class DeploymentHandle:
    """Client handle routing calls across a deployment's replicas."""

    def __init__(self, deployment_name: str, replicas: list,
                 method_name: str = "__call__"):
        self._name = deployment_name
        self._replicas = list(replicas)
        self._method = method_name
        self._rr = itertools.count()

    def options(self, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._name, self._replicas, method_name)

    def remote(self, *args, **kwargs):
        if not self._replicas:
            raise RuntimeError(f"deployment {self._name} has no replicas")
        index = next(self._rr) % len(self._replicas)
        replica = self._replicas[index]
        return replica.handle_request.remote(self._method, args, kwargs)

    def __reduce__(self):
        return (DeploymentHandle,
                (self._name, self._replicas, self._method))


# ---------------------------------------------------------------- actors

class Replica:
    """One replica actor wrapping the user's callable/class
    (ref: serve/_private/replica.py:1124)."""

    def __init__(self, cls_or_fn, args, kwargs):
        if isinstance(cls_or_fn, type):
            self._instance = cls_or_fn(*args, **kwargs)
        else:
            self._instance = cls_or_fn  # plain function deployment

    def handle_request(self, method_name: str, args, kwargs):
        if method_name == "__call__":
            return self._instance(*args, **kwargs)
        return getattr(self._instance, method_name)(*args, **kwargs)

    def health(self):
        return "ok"


class ServeController:
    """Reconciles deployments → replica actors
    (ref: serve/_private/controller.py:105)."""

    def __init__(self):
        self._deployments: dict[str, dict] = {}
        self._proxy = None

    def deploy(self, deployment: Deployment, args, kwargs) -> dict:
        art = _art()
        replica_cls = art.remote(Replica).options(
            **{"num_cpus": deployment.ray_actor_options.get("num_cpus", 0)})
        existing = self._deployments.get(deployment.name)
        if existing is not None:
            for r in existing["replicas"]:
                try:
                    art.kill(r)
                except Exception:  # noqa: BLE001
                    pass
        replicas = [
            replica_cls.remote(deployment.cls_or_fn, args, kwargs)
            for _ in range(deployment.num_replicas)
        ]
        art.get([r.health.remote() for r in replicas])  # readiness gate
        self._deployments[deployment.name] = {
            "deployment": deployment,
            "replicas": replicas,
            "route_prefix": deployment.route_prefix,
        }
        return {"name": deployment.name}

    def get_handle_info(self, name: str):
        entry = self._deployments.get(name)
        if entry is None:
            return None
        return {"replicas": entry["replicas"]}

    def list_deployments(self):
        return {
            name: {
                "num_replicas": len(e["replicas"]),
                "route_prefix": e["route_prefix"],
            }
            for name, e in self._deployments.items()
        }

    def routes(self):
        return {
            e["route_prefix"]: name
            for name, e in self._deployments.items()
            if e["route_prefix"]
        }

    def start_http_proxy(self, port: int) -> int:
        art = _art()
        if self._proxy is None:
            proxy_cls = art.remote(HttpProxy).options(
                max_concurrency=32, num_cpus=0)
            controller = art.get_actor(CONTROLLER_NAME,
                                       namespace="_serve")
            self._proxy = proxy_cls.remote(controller)
        return art.get(self._proxy.start.remote(port))

    def shutdown_all(self):
        art = _art()
        for entry in self._deployments.values():
            for r in entry["replicas"]:
                try:
                    art.kill(r)
                except Exception:  # noqa: BLE001
                    pass
        if self._proxy is not None:
            try:
                art.kill(self._proxy)
            except Exception:  # noqa: BLE001
                pass
        self._deployments.clear()
        return True


class HttpProxy:
    """aiohttp ingress routing requests to deployments by route prefix
    (ref: serve/_private/proxy.py)."""

    def __init__(self, controller):
        self._controller = controller
        self._port = None
        self._runner = None

    def start(self, port: int) -> int:
        import asyncio  # noqa: PLC0415
        import threading  # noqa: PLC0415

        from aiohttp import web  # noqa: PLC0415

        art = _art()
        loop = asyncio.new_event_loop()

        def dispatch(path: str, body):
            """Blocking route+call (runs on an executor thread so the
            aiohttp loop stays free)."""
            routes = art.get(self._controller.routes.remote())
            for prefix, name in routes.items():
                if path.startswith(prefix):
                    info = art.get(
                        self._controller.get_handle_info.remote(name))
                    handle = DeploymentHandle(name, info["replicas"])
                    return {"result": art.get(handle.remote(body))}, 200
            return {"error": f"no route for {path}"}, 404

        async def handler(request: "web.Request"):
            try:
                body = await request.json() if request.can_read_body else {}
            except Exception:  # noqa: BLE001
                body = {}
            loop_ = asyncio.get_running_loop()
            payload, status = await loop_.run_in_executor(
                None, dispatch, request.path, body)
            return web.json_response(payload, status=status)

        app = web.Application()
        app.router.add_route("*", "/{tail:.*}", handler)
        started = threading.Event()
        port_holder = {}

        def _serve():
            asyncio.set_event_loop(loop)
            runner = web.AppRunner(app)
            loop.run_until_complete(runner.setup())
            site = web.TCPSite(runner, "127.0.0.1", port)
            loop.run_until_complete(site.start())
            port_holder["port"] = site._server.sockets[0].getsockname()[1]
            self._runner = runner
            started.set()
            loop.run_forever()

        threading.Thread(target=_serve, daemon=True).start()
        started.wait(10)
        self._port = port_holder.get("port")
        return self._port


# ---------------------------------------------------------------- run api

def _get_or_create_controller():
    art = _art()
    try:
        return art.get_actor(CONTROLLER_NAME, namespace="_serve")
    except ValueError:
        controller_cls = art.remote(ServeController).options(
            name=CONTROLLER_NAME, namespace="_serve", get_if_exists=True,
            max_concurrency=16, num_cpus=0, lifetime="detached")
        return controller_cls.remote()


def run(app: Application, *, port: int | None = None) -> DeploymentHandle:
    """Deploy an application; returns its handle (ref: serve.run)."""
    art = _art()
    if not art.is_initialized():
        art.init()
    controller = _get_or_create_controller()
    art.get(controller.deploy.remote(app.deployment, app.args, app.kwargs))
    if port is not None or app.deployment.route_prefix:
        actual = art.get(controller.start_http_proxy.remote(
            8000 if port is None else port))
        run.last_http_port = actual  # discoverable for tests/clients
    info = art.get(
        controller.get_handle_info.remote(app.deployment.name))
    return DeploymentHandle(app.deployment.name, info["replicas"])


run.last_http_port = None


def shutdown():
    art = _art()
    try:
        controller = art.get_actor(CONTROLLER_NAME, namespace="_serve")
    except ValueError:
        return
    try:
        art.get(controller.shutdown_all.remote())
        art.kill(controller)
    except Exception:  # noqa: BLE001
        pass
