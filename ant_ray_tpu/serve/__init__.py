"""Model serving (ref capability: ray.serve — controller/replica
reconciliation, deployment handles, HTTP ingress)."""

from ant_ray_tpu.serve.api import (
    Application,
    Deployment,
    DeploymentHandle,
    deployment,
    run,
    shutdown,
)

__all__ = [
    "Application",
    "Deployment",
    "DeploymentHandle",
    "deployment",
    "run",
    "shutdown",
]
