"""Model serving (ref capability: ray.serve — controller/replica
reconciliation, deployment handles, HTTP ingress)."""

from ant_ray_tpu.serve.api import (
    CONTROLLER_NAME,
    Application,
    AutoscalingConfig,
    CircuitBreakerConfig,
    Deployment,
    DeploymentHandle,
    RequestRetryConfig,
    batch,
    deployment,
    get_multiplexed_model_id,
    get_request_deadline,
    multiplexed,
    run,
    shutdown,
)

__all__ = [
    "CONTROLLER_NAME",
    "Application",
    "AutoscalingConfig",
    "CircuitBreakerConfig",
    "Deployment",
    "DeploymentHandle",
    "RequestRetryConfig",
    "batch",
    "deployment",
    "get_multiplexed_model_id",
    "get_request_deadline",
    "multiplexed",
    "run",
    "shutdown",
]
