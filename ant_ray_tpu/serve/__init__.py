"""Model serving (ref capability: ray.serve — controller/replica
reconciliation, deployment handles, HTTP ingress)."""

from ant_ray_tpu.serve.api import (
    CONTROLLER_NAME,
    Application,
    AutoscalingConfig,
    Deployment,
    DeploymentHandle,
    batch,
    deployment,
    get_multiplexed_model_id,
    multiplexed,
    run,
    shutdown,
)

__all__ = [
    "CONTROLLER_NAME",
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentHandle",
    "batch",
    "deployment",
    "get_multiplexed_model_id",
    "multiplexed",
    "run",
    "shutdown",
]
