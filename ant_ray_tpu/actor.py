"""Actor API: ActorClass / ActorHandle / ActorMethod
(ref: python/ray/actor.py:1228,1538)."""

from __future__ import annotations

from typing import Any

from ant_ray_tpu._private.ids import ActorID
from ant_ray_tpu._private.task_options import ActorOptions, TaskOptions


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1, concurrency_group: str = ""):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        return global_worker.submit_actor_task(
            self._handle, self._method_name, args, kwargs,
            TaskOptions(num_returns=self._num_returns,
                        concurrency_group=self._concurrency_group),
        )

    def options(self, **options) -> "ActorMethod":
        num_returns = options.pop("num_returns", self._num_returns)
        group = options.pop("concurrency_group", self._concurrency_group)
        if options:
            raise ValueError(
                f"Unsupported actor-method options: {sorted(options)}")
        return ActorMethod(self._handle, self._method_name, num_returns,
                           group)

    def bind(self, *args, **kwargs):
        try:
            from ant_ray_tpu.dag import ActorMethodNode  # noqa: PLC0415
        except ImportError as e:
            raise RuntimeError(
                "The DAG layer is not available in this build") from e
        return ActorMethodNode(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    """Serializable handle to a running actor (ref: actor handles are
    first-class values that can be passed to other tasks/actors)."""

    def __init__(self, actor_id: ActorID, class_name: str,
                 method_names: tuple[str, ...] = (), max_concurrency: int = 1,
                 method_num_returns: dict[str, int] | None = None,
                 max_task_retries: int = 0,
                 method_concurrency_groups: dict[str, str] | None = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_names = tuple(method_names)
        self._max_concurrency = max_concurrency
        self._method_num_returns = dict(method_num_returns or {})
        self._max_task_retries = max_task_retries
        self._method_concurrency_groups = dict(method_concurrency_groups or {})

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    @property
    def class_name(self) -> str:
        return self._class_name

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if self._method_names and name not in self._method_names:
            raise AttributeError(
                f"Actor {self._class_name} has no method {name!r}"
            )
        method = ActorMethod(self, name,
                             self._method_num_returns.get(name, 1),
                             self._method_concurrency_groups.get(name, ""))
        # Memoize in the instance dict: __getattr__ only fires on a
        # MISS, so every later ``handle.ping`` is a plain attribute
        # load — building a wrapper per call is measurable at 10k
        # calls/s.  (Pickling stays shape-stable: __reduce__ rebuilds
        # from ids, never from __dict__.)
        self.__dict__[name] = method
        return method

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._class_name, self._method_names,
             self._max_concurrency, self._method_num_returns,
             self._max_task_retries, self._method_concurrency_groups),
        )

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    """A class decorated with ``@art.remote``; instantiate with ``.remote()``."""

    def __init__(self, cls: type, options: ActorOptions | None = None):
        self._cls = cls
        self._options = options or ActorOptions()
        self._class_name = cls.__name__

    @property
    def cls(self) -> type:
        return self._cls

    @property
    def options_(self) -> ActorOptions:
        return self._options

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._class_name} cannot be instantiated directly; "
            f"use {self._class_name}.remote(...)"
        )

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        return global_worker.create_actor(self, args, kwargs, self._options)

    def options(self, **options) -> "ActorClass":
        return ActorClass(self._cls, self._options.merged_with(**options))

    def method_names(self) -> tuple[str, ...]:
        return tuple(
            name for name in dir(self._cls)
            if callable(getattr(self._cls, name, None)) and not name.startswith("__")
        )

    def method_num_returns(self) -> dict[str, int]:
        """Per-method num_returns declared with ``@method(num_returns=N)``."""
        out = {}
        for name in self.method_names():
            n = getattr(getattr(self._cls, name), "__art_num_returns__", 1)
            if n != 1:
                out[name] = n
        return out

    def method_concurrency_groups(self) -> dict[str, str]:
        """Per-method group declared with ``@method(concurrency_group=...)``."""
        out = {}
        for name in self.method_names():
            g = getattr(getattr(self._cls, name),
                        "__art_concurrency_group__", "")
            if g:
                out[name] = g
        return out


def exit_actor():
    """Terminate the current actor from inside one of its methods
    (ref: ray.actor.exit_actor)."""
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    global_worker.exit_current_actor()
