"""Multi-node-on-one-host test cluster (ref: python/ray/cluster_utils.py:135
— the mechanism by which all distributed scheduling/FT tests run without
real machines: N node daemons, each a full node, on one host)."""

from __future__ import annotations

import json
import os
import subprocess

from ant_ray_tpu._private import services
from ant_ray_tpu._private.protocol import ClientPool


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None):
        self._session_dir = services.new_session_dir()
        self._procs: list[subprocess.Popen] = []
        self._node_addresses: list[str] = []
        self.gcs_address: str | None = None
        self._pool = ClientPool()
        self._saved_env: list[tuple[str, str | None]] = []
        head_node_args = dict(head_node_args or {})
        # _system_config flags travel to every daemon this cluster spawns
        # as ART_<NAME> env vars — same channel api.init uses
        # (ref: _system_config embedded into raylet launch,
        # services.py:1518).
        for key, value in (head_node_args.pop("_system_config", None)
                           or {}).items():
            name = f"ART_{key.upper()}"
            self._saved_env.append((name, os.environ.get(name)))
            os.environ[name] = (json.dumps(value)
                                if isinstance(value, (dict, list))
                                else str(value))
        if initialize_head:
            self.add_node(**head_node_args)

    @property
    def address(self) -> str:
        assert self.gcs_address is not None, "cluster has no head"
        return self.gcs_address

    def add_node(self, num_cpus: int | None = None,
                 num_tpus: int | None = None,
                 resources: dict | None = None,
                 labels: dict | None = None) -> str:
        """Start one more node daemon; the first call also starts the GCS."""
        if self.gcs_address is None:
            gcs_proc, self.gcs_address = services.start_gcs(self._session_dir)
            self._procs.append(gcs_proc)
        node_resources = services.default_resources(
            num_cpus if num_cpus is not None else 1, num_tpus, resources)
        proc, address = services.start_node(
            self.gcs_address, node_resources, self._session_dir, labels)
        self._procs.append(proc)
        self._node_addresses.append(address)
        return address

    def kill_gcs(self) -> None:
        """Kill the head's GCS process (simulates head failure)."""
        assert self.gcs_address is not None
        proc = self._procs[0]
        proc.kill()
        proc.wait(timeout=5)

    def restart_gcs(self) -> None:
        """Restart the GCS on the same port, resuming from its sqlite
        store (the test_gcs_fault_tolerance scenario)."""
        assert self.gcs_address is not None
        port = int(self.gcs_address.rsplit(":", 1)[1])
        proc, address = services.start_gcs(self._session_dir, port=port)
        self._procs[0] = proc
        assert address == self.gcs_address

    def drain_node(self, address: str, reason: str = "preemption",
                   deadline_s: float = 30.0) -> None:
        """Inject a drain/preemption notice into one node daemon (the
        announced-departure scenario: a TPU maintenance event fires
        minutes before the host dies).  The node stops taking new
        leases; Serve and Train migrate off it."""
        self._pool.get(address).call(
            "NotifyDrain", {"reason": reason, "deadline_s": deadline_s},
            timeout=10)

    def remove_node(self, address: str, graceful: bool = False) -> None:
        """Kill a node daemon (simulates node failure when not graceful)."""
        index = self._node_addresses.index(address)
        proc = self._procs[1 + index]  # procs[0] is the GCS
        if graceful:
            try:
                self._pool.get(address).call("Shutdown", timeout=2)
            except Exception:  # noqa: BLE001
                pass
            proc.terminate()
        else:
            proc.kill()
        proc.wait(timeout=5)

    def connect(self, **init_kwargs):
        import ant_ray_tpu as art  # noqa: PLC0415

        return art.init(address=self.address, **init_kwargs)

    def shutdown(self):
        self._pool.close_all()
        services.stop_processes(self._procs)
        self._procs.clear()
        self._node_addresses.clear()
        self.gcs_address = None
        for name, old in self._saved_env:
            if old is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = old
        self._saved_env.clear()
