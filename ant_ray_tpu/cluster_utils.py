"""Multi-node-on-one-host test cluster (ref: python/ray/cluster_utils.py:135
— the mechanism by which all distributed scheduling/FT tests run without
real machines: N node daemons, each a full node, on one host).

With ``head_node_args={"gcs_standbys": N}`` the control plane itself is
replicated: N+1 GCS processes share the session store, the lease elects
a leader, and every address handed to daemons/drivers is the full
comma-joined replica list — so ``kill_gcs_leader()`` exercises a real
failover, not a restart."""

from __future__ import annotations

import json
import os
import subprocess

from ant_ray_tpu._private import services
from ant_ray_tpu._private.protocol import ClientPool


def _descendant_pids(root_pid: int) -> list[int]:
    """Every live descendant of ``root_pid`` (workers, agents, ...),
    via one /proc scan.  Workers detach into their own sessions
    (``start_new_session=True``) so a process-group kill can't reach
    them — but their PPID still names the daemon that spawned them."""
    children: dict[int, list[int]] = {}
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat") as f:
                # "pid (comm) state ppid ..." — comm may itself contain
                # parens/spaces, so split off the LAST ')'.
                ppid = int(f.read().rsplit(")", 1)[1].split()[1])
        except (OSError, IndexError, ValueError):
            continue
        children.setdefault(ppid, []).append(int(entry))
    out: list[int] = []
    stack = [root_pid]
    while stack:
        for child in children.get(stack.pop(), ()):
            out.append(child)
            stack.append(child)
    return out


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None):
        self._session_dir = services.new_session_dir()
        self._gcs_procs: list[tuple[subprocess.Popen, str]] = []
        self._node_procs: list[subprocess.Popen] = []
        self._node_addresses: list[str] = []
        self._node_labels: list[dict] = []
        self._gcs_standbys = 0
        self._gcs_replica_seq = 0
        self._pool = ClientPool()
        self._saved_env: list[tuple[str, str | None]] = []
        head_node_args = dict(head_node_args or {})
        self._gcs_standbys = int(head_node_args.pop("gcs_standbys", 0))
        # _system_config flags travel to every daemon this cluster spawns
        # as ART_<NAME> env vars — same channel api.init uses
        # (ref: _system_config embedded into raylet launch,
        # services.py:1518).
        for key, value in (head_node_args.pop("_system_config", None)
                           or {}).items():
            name = f"ART_{key.upper()}"
            self._saved_env.append((name, os.environ.get(name)))
            os.environ[name] = (json.dumps(value)
                                if isinstance(value, (dict, list))
                                else str(value))
        if initialize_head:
            self.add_node(**head_node_args)

    @property
    def gcs_address(self) -> str | None:
        """The GCS endpoint spec handed to daemons/drivers: a single
        address, or the comma-joined replica list when standbys exist
        (ClientPool resolves that spec to a leader-aware router)."""
        if not self._gcs_procs:
            return None
        return ",".join(addr for _proc, addr in self._gcs_procs)

    @property
    def address(self) -> str:
        assert self._gcs_procs, "cluster has no head"
        return self.gcs_address

    # ------------------------------------------------------------ members

    def _start_gcs_replica(self) -> str:
        replica_id = f"r{self._gcs_replica_seq}"
        self._gcs_replica_seq += 1
        ha = self._gcs_standbys > 0 or self._gcs_replica_seq > 1
        proc, address = services.start_gcs(
            self._session_dir,
            ha_replica_id=replica_id if ha else None)
        self._gcs_procs.append((proc, address))
        return address

    def add_node(self, num_cpus: int | None = None,
                 num_tpus: int | None = None,
                 resources: dict | None = None,
                 labels: dict | None = None) -> str:
        """Start one more node daemon; the first call also starts the
        GCS (plus any configured standbys)."""
        if not self._gcs_procs:
            self._start_gcs_replica()
            for _ in range(self._gcs_standbys):
                self._start_gcs_replica()
        node_resources = services.default_resources(
            num_cpus if num_cpus is not None else 1, num_tpus, resources)
        proc, address = services.start_node(
            self.gcs_address, node_resources, self._session_dir, labels)
        self._node_procs.append(proc)
        self._node_addresses.append(address)
        self._node_labels.append(dict(labels or {}))
        return address

    def add_gcs_standby(self) -> str:
        """Grow the control-plane replica set by one warm standby.
        Existing clients learn it through their next HA-view refresh;
        new daemons/drivers get it in the address spec."""
        assert self._gcs_procs, "start a head first"
        # The head must itself be lease-electing: a standby beside a
        # non-HA head would grab the (uncontested) lease and split-brain.
        assert self._gcs_standbys > 0, \
            "construct the Cluster with head_node_args={'gcs_standbys': N}"
        return self._start_gcs_replica()

    # ---------------------------------------------------------- GCS chaos

    def kill_gcs(self) -> None:
        """Kill the head's (first) GCS process (simulates head failure
        in the single-replica restart-FT scenario)."""
        assert self._gcs_procs
        proc, _addr = self._gcs_procs[0]
        proc.kill()
        proc.wait(timeout=5)

    def restart_gcs(self) -> None:
        """Restart the GCS on the same port, resuming from its sqlite
        store (the test_gcs_fault_tolerance scenario).  On a replicated
        cluster the restarted process rejoins as an HA replica (fresh
        id) — restarting it lease-less beside live standbys would make
        it an unfenced second leader over the same store."""
        assert self._gcs_procs
        old_proc, old_addr = self._gcs_procs[0]
        port = int(old_addr.rsplit(":", 1)[1])
        replica_id = None
        if self._gcs_standbys > 0:
            replica_id = f"r{self._gcs_replica_seq}"
            self._gcs_replica_seq += 1
        proc, address = services.start_gcs(self._session_dir, port=port,
                                           ha_replica_id=replica_id)
        assert address == old_addr
        self._gcs_procs[0] = (proc, address)

    def gcs_leader_address(self, timeout: float = 10.0) -> str:
        """The current leader's address, per whichever replica answers
        the HA view first."""
        import time

        deadline = time.monotonic() + timeout
        last_err: Exception | None = None
        while time.monotonic() < deadline:
            for _proc, addr in self._gcs_procs:
                try:
                    view = self._pool.get(addr).call("GetHaView", {},
                                                     timeout=2)
                except Exception as e:  # noqa: BLE001 — replica down
                    last_err = e
                    continue
                # Only the leader's own word counts: a standby's view
                # can still name the replica that just died.
                if view.get("role") == "leader":
                    return view["address"]
            time.sleep(0.1)
        raise RuntimeError(f"no GCS leader elected: {last_err}")

    def kill_gcs_leader(self) -> str:
        """Find the current leader, SIGKILL it, and return its address
        — the control-plane loss the replicated GCS must absorb.  The
        dead replica stays out of the set (no restart): failover, not
        recovery, is under test."""
        leader = self.gcs_leader_address()
        for index, (proc, addr) in enumerate(self._gcs_procs):
            if addr == leader:
                proc.kill()
                proc.wait(timeout=5)
                del self._gcs_procs[index]
                return addr
        raise RuntimeError(f"leader {leader} is not one of this "
                           "cluster's GCS processes")

    # -------------------------------------------------------------- nodes

    def drain_node(self, address: str, reason: str = "preemption",
                   deadline_s: float = 30.0) -> None:
        """Inject a drain/preemption notice into one node daemon (the
        announced-departure scenario: a TPU maintenance event fires
        minutes before the host dies).  The node stops taking new
        leases; Serve and Train migrate off it."""
        self._pool.get(address).call(
            "NotifyDrain", {"reason": reason, "deadline_s": deadline_s},
            timeout=10)

    def remove_node(self, address: str, graceful: bool = False) -> None:
        """Kill a node daemon (simulates node failure when not graceful)."""
        index = self._node_addresses.index(address)
        proc = self._node_procs[index]
        if graceful:
            try:
                self._pool.get(address).call("Shutdown", timeout=2)
            except Exception:  # noqa: BLE001
                pass
            proc.terminate()
        else:
            proc.kill()
        proc.wait(timeout=5)

    def nodes_with_label(self, key: str, value: str) -> list[str]:
        """Addresses of live node daemons started with label
        ``key=value`` (e.g. every host of one simulated TPU slice)."""
        return [addr
                for addr, labels, proc in zip(self._node_addresses,
                                              self._node_labels,
                                              self._node_procs)
                if labels.get(key) == value and proc.poll() is None]

    def kill_slice(self, slice_id: str,
                   label: str = "art-slice-id") -> list[str]:
        """SIGKILL every process of every node labeled as slice
        ``slice_id`` — the whole-slice failure domain of multi-slice
        training (one DCN-linked slice loses power as a UNIT, taking
        daemon, agent AND workers with it; single-node kills never
        exercise the gang's all-ranks-at-once recovery).  Unlike
        ``remove_node`` — whose orphaned workers model a daemon crash
        and suicide only after their lagged liveness poll — power loss
        is instantaneous, so the daemon's whole process tree dies
        first.  Returns the killed addresses."""
        import signal

        victims = self.nodes_with_label(label, str(slice_id))
        if not victims:
            raise RuntimeError(
                f"no live nodes labeled {label}={slice_id!r}")
        for address in victims:
            daemon = self._node_procs[self._node_addresses.index(address)]
            for pid in _descendant_pids(daemon.pid):
                try:
                    os.kill(pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
            self.remove_node(address)
        return victims

    def connect(self, **init_kwargs):
        import ant_ray_tpu as art  # noqa: PLC0415

        return art.init(address=self.address, **init_kwargs)

    def shutdown(self):
        self._pool.close_all()
        procs = [p for p, _a in self._gcs_procs] + self._node_procs
        services.stop_processes(procs)
        self._gcs_procs.clear()
        self._node_procs.clear()
        self._node_addresses.clear()
        for name, old in self._saved_env:
            if old is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = old
        self._saved_env.clear()
