"""Multi-node-on-one-host test cluster (ref: python/ray/cluster_utils.py:135
— the mechanism by which all distributed scheduling/FT tests run without
real machines: N node daemons, each a full node, on one host)."""

from __future__ import annotations

import subprocess

from ant_ray_tpu._private import services
from ant_ray_tpu._private.protocol import ClientPool


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: dict | None = None):
        self._session_dir = services.new_session_dir()
        self._procs: list[subprocess.Popen] = []
        self._node_addresses: list[str] = []
        self.gcs_address: str | None = None
        self._pool = ClientPool()
        if initialize_head:
            self.add_node(**(head_node_args or {}))

    @property
    def address(self) -> str:
        assert self.gcs_address is not None, "cluster has no head"
        return self.gcs_address

    def add_node(self, num_cpus: int | None = None,
                 num_tpus: int | None = None,
                 resources: dict | None = None,
                 labels: dict | None = None) -> str:
        """Start one more node daemon; the first call also starts the GCS."""
        if self.gcs_address is None:
            gcs_proc, self.gcs_address = services.start_gcs(self._session_dir)
            self._procs.append(gcs_proc)
        node_resources = services.default_resources(
            num_cpus if num_cpus is not None else 1, num_tpus, resources)
        proc, address = services.start_node(
            self.gcs_address, node_resources, self._session_dir, labels)
        self._procs.append(proc)
        self._node_addresses.append(address)
        return address

    def remove_node(self, address: str, graceful: bool = False) -> None:
        """Kill a node daemon (simulates node failure when not graceful)."""
        index = self._node_addresses.index(address)
        proc = self._procs[1 + index]  # procs[0] is the GCS
        if graceful:
            try:
                self._pool.get(address).call("Shutdown", timeout=2)
            except Exception:  # noqa: BLE001
                pass
            proc.terminate()
        else:
            proc.kill()
        proc.wait(timeout=5)

    def connect(self, **init_kwargs):
        import ant_ray_tpu as art  # noqa: PLC0415

        return art.init(address=self.address, **init_kwargs)

    def shutdown(self):
        self._pool.close_all()
        services.stop_processes(self._procs)
        self._procs.clear()
        self._node_addresses.clear()
        self.gcs_address = None
