"""ClusterRuntime — the in-process runtime for drivers and workers.

Role of the reference's CoreWorker (ref: src/ray/core_worker/core_worker.h:167):
task/actor submission with leases and per-actor ordered pipelining, the
owner-side memory store, the put/get object paths (inline, shm plasma, remote
pull), borrower registration, and reference counting that frees objects
cluster-wide when the last handle dies.

Every driver/worker process runs one "core service" RPC server so borrowers
can fetch owned objects directly from their owner (ownership-based object
resolution — ref: OwnershipObjectDirectory).
"""

from __future__ import annotations

import hashlib
import logging
import os
import threading
import time
import uuid
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Sequence

import asyncio

from ant_ray_tpu import exceptions
from ant_ray_tpu._private import serialization
from ant_ray_tpu._private.config import Config, global_config
from ant_ray_tpu._private.ids import (
    ActorID,
    JobID,
    ObjectID,
    TaskID,
    WorkerID,
)
from ant_ray_tpu._private import task_events
from ant_ray_tpu._private.memory_store import MemoryStore
from ant_ray_tpu._private.object_store import ArenaClient, open_object
from ant_ray_tpu._private.protocol import (
    ClientPool,
    IoThread,
    RpcConnectionError,
    RpcError,
    RpcServer,
    _spawn,
)
from ant_ray_tpu._private.specs import (
    ACTOR_ALIVE,
    ACTOR_DEAD,
    ACTOR_RESTARTING,
    ActorSpec,
    PromotedArgs,
    TaskSpec,
)
from ant_ray_tpu._private.task_options import ActorOptions, TaskOptions
from ant_ray_tpu.util.scheduling_strategies import strategy_wire
from ant_ray_tpu._private.worker import CoreRuntime
from ant_ray_tpu.object_ref import ObjectRef, set_refcount_hook
from ant_ray_tpu.observability import tracing_plane

logger = logging.getLogger(__name__)


class _AllCopiesLost(Exception):
    """Internal: EnsureLocal reported an empty holder list — every copy
    of the plasma object is gone; try lineage reconstruction."""

    def __init__(self, oid: ObjectID):
        super().__init__(oid.hex())
        self.oid = oid


@dataclass
class _StreamState:
    """Owner-side bookkeeping of one streaming task's returns
    (ref: ObjectRefStream, src/ray/core_worker/task_manager.h:67)."""

    received: int = 0                  # contiguous items stored so far
    total: int | None = None           # set by the end-of-stream marker
    error: Exception | None = None     # mid-stream task failure
    cond: threading.Condition = field(
        default_factory=threading.Condition)


@dataclass
class _SchedKeyState:
    """Per-scheduling-key task queue + leased-worker pool (ref:
    NormalTaskSubmitter's scheduling_key_entries_,
    task_submission/normal_task_submitter.h:295 — tasks with the same
    (resources, runtime_env, placement, labels) share worker leases
    instead of paying a lease/return RPC pair each)."""

    resources: dict
    runtime_env: Any
    label_selector: dict | None
    pg: tuple | None                  # (pg_id, bundle_index) if any
    strategy: Any = None              # wire-form scheduling strategy
    queue: deque = field(default_factory=deque)  # (spec, pinned, attempt)
    workers: int = 0                  # granted leases currently draining
    busy: int = 0                     # of those, executing a task now
    acquiring: int = 0                # LeaseWorker requests in flight
    wakeup: asyncio.Event = field(default_factory=asyncio.Event)


@dataclass
class _ActorSubmitState:
    """Per-actor ordered submission queue
    (ref: ActorTaskSubmitter, task_submission/actor_task_submitter.h:68)."""

    actor_id: ActorID
    address: str = ""
    next_seq: int = 0
    queue: deque = field(default_factory=deque)
    sender_running: bool = False
    dead_reason: str | None = None


# Precomputed wire form of "no arguments" — the most common actor-call
# shape; skips a serializer pass per call.
_EMPTY_ARGS_PAYLOAD = serialization.serialize(((), {})).to_payload()

_FRAMEWORK_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _creation_callsite(limit: int = 12) -> str | None:
    """First stack frame OUTSIDE the framework — the user line that
    created the object (behind config.record_object_callsite; walked
    only when the knob is on)."""
    import sys  # noqa: PLC0415

    frame = sys._getframe(1)
    for _ in range(limit):
        if frame is None:
            return None
        filename = frame.f_code.co_filename
        if not filename.startswith(_FRAMEWORK_DIR):
            return f"{filename}:{frame.f_lineno}"
        frame = frame.f_back
    return None


class _ArenaPin:
    """Owner of one daemon-side arena read pin.  Values deserialized
    zero-copy from the pinned window hold this object (via
    serialization pinned-buffer bases); when the last of them is GC'd
    the finalizer ships ReadDone, letting the store evict the slot.
    While alive it sits in the runtime's live-pin set, whose renewal
    loop heartbeats RenewPin so a long-held value (e.g. model weights
    for a whole run) never outlives its daemon-side lease."""

    __slots__ = ("_finalizer", "oid", "token", "__weakref__")

    def __init__(self, release, oid, token):
        self.oid = oid
        self.token = token
        self._finalizer = weakref.finalize(self, release)


class _BlockedCtx:
    """Blocked-in-get() marker for the node daemon (module-level: this is
    entered on every get(), so it must not define classes or closures)."""

    __slots__ = ("_runtime",)

    def __init__(self, runtime):
        self._runtime = runtime

    def __enter__(self):
        runtime = self._runtime
        if runtime.role == "worker" and runtime.worker_id is not None:
            with runtime._blocked_lock:
                runtime._blocked_depth += 1
                if runtime._blocked_depth == 1:
                    runtime._send_oneway(
                        runtime.node_address, "WorkerBlocked",
                        {"worker_id": runtime.worker_id})
        return self

    def __exit__(self, *exc):
        runtime = self._runtime
        if runtime.role == "worker" and runtime.worker_id is not None:
            with runtime._blocked_lock:
                runtime._blocked_depth -= 1
                if runtime._blocked_depth == 0:
                    runtime._send_oneway(
                        runtime.node_address, "WorkerUnblocked",
                        {"worker_id": runtime.worker_id})


class ClusterRuntime(CoreRuntime):
    def __init__(self, *, role: str, job_id: JobID, gcs_address: str,
                 node_address: str, store_dir: str,
                 worker_id: WorkerID | None = None,
                 owned_processes: list | None = None,
                 session_dir: str = ""):
        self.role = role
        self.job_id = job_id
        self._io = IoThread.get()
        self._clients = ClientPool()
        self._gcs = self._clients.get(gcs_address)
        self._node = self._clients.get(node_address)
        self.gcs_address = gcs_address
        self.node_address = node_address
        self.store_dir = store_dir
        self.worker_id = worker_id
        self._owned_processes = owned_processes or []
        self.session_dir = session_dir

        self.memory = MemoryStore(self._io.loop)
        self.server = RpcServer()
        self.server.routes({
            # Liveness probe (the node daemon's lease-owner sweep pings
            # lessees; an unroutable Ping would read as "owner dead").
            "Ping": self._handle_ping,
            "GetObject": self._handle_get_object,
            "GetObjectStatus": self._handle_get_object_status,
            "GetObjectStatusBatch": self._handle_get_object_status_batch,
            "WaitObjects": self._handle_wait_objects,
            "GetObjectInfo": self._handle_get_object_info,
            "GetOwnedRefInfo": self._handle_get_owned_ref_info,
            "BorrowAdd": self._handle_borrow_add,
            "BorrowRemove": self._handle_borrow_remove,
            "ReconstructObject": self._handle_reconstruct_object,
            "DeviceTensorFetch": self._handle_device_tensor_fetch,
            "DeviceTensorFree": self._handle_device_tensor_free,
            "DeviceTensorSendVia": self._handle_device_tensor_send_via,
            "StreamItem": self._handle_stream_item,
        })
        self._streams: dict[TaskID, _StreamState] = {}
        # abandoned stream ids (insertion-ordered; bounded) — late items
        # for these are dropped, not stored
        self._released_streams: dict[TaskID, bool] = {}
        # HBM-resident objects held by this worker, keyed by holder
        # token, plus the metadata-oid → token map that ties payload
        # lifetime to the metadata object's refcount
        # (see experimental/device_objects.py)
        self._device_objects: dict[str, Any] = {}
        self._device_tokens_by_oid: dict[ObjectID, str] = {}
        self.address = self.server.start()

        self._driver_task_id = TaskID.for_driver_task(job_id)
        self._put_index = 0
        from ant_ray_tpu._lint.lockcheck import make_lock, make_rlock  # noqa: PLC0415

        self._put_lock = make_lock("core.put_index")

        # ---- reference counting state (owner side)
        self._local_refs: dict[ObjectID, int] = {}
        self._borrows: dict[ObjectID, int] = {}       # borrows of objects I own
        self._pins: dict[ObjectID, int] = {}          # in-flight task args
        # nested refs pinned for the lifetime of an owned outer object
        # (put() of a value containing refs) — released when the outer
        # object is freed, so inner objects don't leak (ref: nested-ref
        # release in ReferenceCounter, reference_counter.h:44)
        self._contained_pins: dict[ObjectID, list] = {}
        # refs pinned inside actor-constructor args — released when the
        # actor can no longer restart (killed or permanently dead)
        self._actor_ctor_pins: dict[ActorID, list] = {}
        self._borrowed_from: dict[ObjectID, str] = {} # owner addr of my borrows
        # Reentrant: dropping the last Python reference to an ObjectRef
        # *inside* a locked region (e.g. releasing a _contained_pins list,
        # or a cyclic-GC pass triggered by any allocation while the lock
        # is held) fires ObjectRef.__del__ → _refcount_event on the same
        # thread; a plain Lock self-deadlocks there.  The nested calls
        # only do per-key dict ops, which compose safely.
        self._ref_lock = make_rlock("core.refcount")
        set_refcount_hook(self._refcount_event)

        # ---- function/class export
        self._fetch_cache: dict[str, Any] = {}        # kv key -> callable/class

        # ---- lineage (owner side): plasma return -> producing TaskSpec,
        # re-executed when every copy of the object is lost
        # (ref: TaskManager lineage + ObjectRecoveryManager,
        #  src/ray/core_worker/object_recovery_manager.h:98-108)
        self._lineage: dict[ObjectID, TaskSpec] = {}
        self._reconstructions: dict[TaskID, asyncio.Future] = {}

        self._sched_states: dict[tuple, _SchedKeyState] = {}
        self._actor_states: dict[ActorID, _ActorSubmitState] = {}
        # Cross-thread submission inbox: app threads append, one
        # call_soon_threadsafe wakeup drains the burst — a wakeup per
        # call is an eventfd syscall each, visible at 10k calls/s.
        self._submit_inbox: deque = deque()
        self._inbox_scheduled = False  # GIL-atomic flag
        # Coalesced best-effort oneway publishes (refcount borrows,
        # cluster-wide frees): any thread appends, one io-loop drain
        # groups the burst per destination and ships each group as ONE
        # transport write — per-event frames and wakeups are visible at
        # 10k calls/s.  A single sequential drainer preserves per-
        # destination ordering (BorrowAdd before BorrowRemove).
        self._oneway_inbox: deque = deque()
        self._oneway_scheduled = False  # GIL-atomic flag
        self._oneway_draining = False   # io-loop confined
        # Shared bound method for the per-call reply callback: binding
        # once avoids a closure + bound-method allocation per call on
        # the actor-reply hot path.
        self._actor_reply_cb = self._on_actor_reply_done
        self._actor_meta_cache: dict[ActorID, dict] = {}
        self._pg_bundle_cache: dict = {}  # pg_id -> [node addresses]
        self._renv_cache: dict = {}       # runtime_env -> wire form
        self._arena_client = ArenaClient()
        # Live zero-copy pins (weak: pins die when their values are
        # GC'd); the renewal loop heartbeats their daemon leases.
        self._live_pins = weakref.WeakSet()
        self._pin_renewer_started = False
        self._blocked_depth = 0
        self._blocked_lock = make_lock("core.blocked_depth")
        self._shutdown = False
        # Long-poll subscription to GCS pubsub channels: actor deaths
        # arrive as pushes, so idle processes make ~0 RPCs/s and failure
        # news beats the next failed call
        # (ref: src/ray/pubsub/publisher.h subscriber side).
        self._pubsub_task = asyncio.run_coroutine_threadsafe(
            self._pubsub_loop(), self._io.loop)

    # ------------------------------------------------------------ bootstrap

    @classmethod
    def create(cls, *, address: str | None, job_id: JobID,
               num_cpus: int | None, num_tpus: int | None,
               resources: dict | None, namespace: str,
               config: Config) -> "ClusterRuntime":
        from ant_ray_tpu._private import services  # noqa: PLC0415

        if address is None:
            boot = services.start_cluster(
                num_cpus=num_cpus, num_tpus=num_tpus, resources=resources)
            gcs_address = boot["gcs_address"]
            node_address = boot["node_address"]
            store_dir = boot["store_dir"]
            owned = boot["processes"]
            session_dir = boot["session_dir"]
            dashboard_url = boot.get("dashboard_url", "")
        else:
            gcs_address = address.removeprefix("art://")
            node_address, store_dir = services.find_local_node(gcs_address)
            owned = []
            session_dir = ""
            dashboard_url = ""

        runtime = cls(role="driver", job_id=job_id, gcs_address=gcs_address,
                      node_address=node_address, store_dir=store_dir,
                      owned_processes=owned, session_dir=session_dir)
        if not dashboard_url:
            blob = runtime._gcs.call("KVGet", {"key": "dashboard_url"},
                                     retries=3)
            dashboard_url = blob.decode() if blob else ""
        runtime.dashboard_url = dashboard_url
        runtime._gcs.call(
            "RegisterJob",
            {"job_id": job_id, "driver_address": runtime.address},
            retries=3)
        return runtime

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        task = getattr(self, "_pubsub_task", None)
        if task is not None:
            task.cancel()
        set_refcount_hook(None)
        from ant_ray_tpu._private import services  # noqa: PLC0415

        if self._owned_processes:
            try:
                self._gcs.call("Shutdown", timeout=2)
            except Exception:  # noqa: BLE001
                pass
            services.stop_processes(self._owned_processes)
        self.server.stop()
        self._clients.close_all()

    # ------------------------------------------------------------ pubsub

    async def _pubsub_loop(self):
        channels = ["actor_state"]
        if self.role == "driver" and global_config().log_to_driver:
            # Drivers also stream worker stdout/stderr lines (ref:
            # log_monitor.py — `print()` in a task appears here).
            channels.append("worker_logs")
        cursor = -1  # start from "now" — no interest in history
        while not self._shutdown:
            try:
                reply = await self._gcs.call_async(
                    "SubPoll", {"channels": tuple(channels),
                                "cursor": cursor, "timeout": 25.0},
                    timeout=35)
            except asyncio.CancelledError:
                return
            except Exception:  # noqa: BLE001 — head restarting
                # A restarted head's sequence restarts at 0; resuming
                # with the old (large) cursor would silence the channel
                # forever.  Resubscribe from "now".
                cursor = -1
                await asyncio.sleep(1.0)
                continue
            cursor = reply["cursor"]
            for _seq, channel, data in reply["events"]:
                try:
                    self._on_pubsub_event(channel, data)
                except Exception:  # noqa: BLE001
                    logger.exception("pubsub event handling failed")

    def _on_pubsub_event(self, channel: str, data: dict) -> None:
        if channel == "worker_logs":
            # Worker output → driver console, ray-style prefixes.
            # Job-scoped: on shared clusters another driver's task
            # output stays off this console (entries without a job tag
            # — e.g. a worker booting — print everywhere).
            node = data.get("node", "?")
            my_job = self.job_id.hex() if self.job_id else None
            for entry in data.get("entries", ()):
                entry_job = entry.get("job_id")
                if entry_job is not None and my_job is not None \
                        and entry_job != my_job:
                    continue
                prefix = f"(worker={entry.get('worker', '?')}" + (
                    f" pid={entry['pid']}" if entry.get("pid") else "") + \
                    f" node={node})"
                for line in entry.get("lines", ()):
                    print(f"{prefix} {line}", flush=True)
            return
        if channel == "actor_state":
            state = self._actor_states.get(data["actor_id"])
            if state is None:
                return
            if data["state"] == ACTOR_DEAD:
                # Push-based death: queued and future calls fail fast
                # instead of each discovering it via its own RPC.
                state.dead_reason = (data.get("death_reason")
                                     or "actor died")
                state.address = ""
                self._release_actor_ctor_pins(data["actor_id"])
            elif data["state"] == ACTOR_RESTARTING:
                state.address = ""
            elif data["state"] == ACTOR_ALIVE and data.get("address"):
                state.address = data["address"]

    # ------------------------------------------------------------ refcount

    def _refcount_event(self, event: str, ref: ObjectRef):
        if self._shutdown:
            return
        oid = ref.id
        with self._ref_lock:
            if event in ("add", "deserialized"):
                self._local_refs[oid] = self._local_refs.get(oid, 0) + 1
                if event == "deserialized" and not self.memory.is_owned(oid):
                    self._borrowed_from[oid] = ref.owner_address
                    self._send_oneway(ref.owner_address, "BorrowAdd",
                                      {"object_id": oid})
            elif event == "remove":
                count = self._local_refs.get(oid, 0) - 1
                if count > 0:
                    self._local_refs[oid] = count
                    return
                self._local_refs.pop(oid, None)
                owner = self._borrowed_from.pop(oid, None)
                if owner is not None:
                    self._send_oneway(owner, "BorrowRemove",
                                      {"object_id": oid})
                elif self.memory.is_owned(oid):
                    self._maybe_free_locked(oid)

    def _maybe_free_locked(self, oid: ObjectID):
        """Free an owned object once local refs, borrows and pins are gone."""
        if (self._local_refs.get(oid, 0) == 0
                and self._borrows.get(oid, 0) == 0
                and self._pins.get(oid, 0) == 0):
            entry = self.memory.get_entry(oid)
            self.memory.delete(oid)
            self._lineage.pop(oid, None)  # freed ⇒ lineage released
            token = self._device_tokens_by_oid.pop(oid, None)
            if token is not None:
                self._device_objects.pop(token, None)  # HBM released
            if entry is not None and entry[0] == "plasma":
                self._send_oneway(self.gcs_address, "FreeObject",
                                  {"object_id": oid})
            # Freeing the outer object releases its nested-ref pins
            # (may cascade into freeing the inner objects too).
            inner = self._contained_pins.pop(oid, None)
            if inner:
                self._unpin_locked(inner)

    def _send_oneway(self, address: str, method: str, payload):
        if not address or address == "local":
            return
        # The flag is cleared on the loop before draining, so an append
        # racing the drain at worst costs a redundant wakeup.
        self._oneway_inbox.append((address, method, payload))
        if not self._oneway_scheduled:
            self._oneway_scheduled = True
            self._io.loop.call_soon_threadsafe(self._kick_oneways)

    def _kick_oneways(self) -> None:
        # io-loop only.  ONE drainer coroutine at a time: interleaved
        # drains could reorder a destination's events (BorrowRemove
        # overtaking its BorrowAdd corrupts refcounts).
        self._oneway_scheduled = False
        if self._oneway_draining:
            return
        self._oneway_draining = True
        # _spawn, not bare ensure_future: the drainer suspends on
        # socket writes and the loop holds only weak task refs.
        _spawn(self._drain_oneways())

    async def _drain_oneways(self) -> None:
        try:
            while self._oneway_inbox:
                grouped: dict[str, list] = {}
                inbox = self._oneway_inbox
                while inbox:
                    address, method, payload = inbox.popleft()
                    grouped.setdefault(address, []).append(
                        (method, payload))
                for address, items in grouped.items():
                    try:
                        await self._clients.get(address).oneway_many(items)
                    except Exception:  # noqa: BLE001 — best-effort msgs
                        pass
        finally:
            self._oneway_draining = False
            if self._oneway_inbox:
                self._kick_oneways()

    async def _handle_ping(self, _payload):
        return "pong"

    async def _handle_borrow_add(self, payload):
        with self._ref_lock:
            oid = payload["object_id"]
            self._borrows[oid] = self._borrows.get(oid, 0) + 1
        return True

    async def _handle_borrow_remove(self, payload):
        with self._ref_lock:
            oid = payload["object_id"]
            count = self._borrows.get(oid, 0) - 1
            if count <= 0:
                self._borrows.pop(oid, None)
                self._maybe_free_locked(oid)
            else:
                self._borrows[oid] = count
        return True

    def _pin(self, refs: Sequence[ObjectRef]):
        with self._ref_lock:
            self._pin_locked(refs)

    def _pin_locked(self, refs: Sequence[ObjectRef]):
        for ref in refs:
            self._pins[ref.id] = self._pins.get(ref.id, 0) + 1

    def _unpin(self, refs: Sequence[ObjectRef]):
        with self._ref_lock:
            self._unpin_locked(refs)

    def _unpin_locked(self, refs: Sequence[ObjectRef]):
        for ref in refs:
            count = self._pins.get(ref.id, 0) - 1
            if count <= 0:
                self._pins.pop(ref.id, None)
                if self.memory.is_owned(ref.id):
                    self._maybe_free_locked(ref.id)
            else:
                self._pins[ref.id] = count

    # ------------------------------------------------------------ export

    def export(self, obj: Any, kind: str) -> str:
        """Export a function/class definition to GCS KV, content-addressed.

        The memo lives on the object itself (never key a cache by id():
        CPython reuses addresses of collected objects, which would hand a
        new function a dead function's export key).
        """
        memo = getattr(obj, "__art_export_key__", None)
        if memo is not None:
            memo_cluster, key = memo
            # The memo is only valid for the cluster it was exported to —
            # a driver that init()s a second cluster must re-upload or
            # workers there will miss the definition.
            if memo_cluster == self.gcs_address:
                return key
        blob = serialization.dumps_code(obj)
        key = f"{kind}:{hashlib.sha256(blob).hexdigest()[:24]}"
        self._gcs.call("KVPut", {"key": key, "value": blob,
                                 "overwrite": False}, retries=3)
        try:
            obj.__art_export_key__ = (self.gcs_address, key)
        except (AttributeError, TypeError):
            pass  # unmemoizable (e.g. builtin): re-pickle next time
        return key

    def fetch_code(self, key: str) -> Any:
        obj = self._fetch_cache.get(key)
        if obj is None:
            blob = self._gcs.call("KVGet", {"key": key}, retries=3)
            if blob is None:
                raise RuntimeError(f"definition {key} not found in GCS KV")
            obj = serialization.loads_code(blob)
            self._fetch_cache[key] = obj
        return obj

    # ------------------------------------------------------------ put/get

    def _next_put_id(self) -> ObjectID:
        with self._put_lock:
            self._put_index += 1
            idx = self._put_index
        return ObjectID.for_task_return(self._driver_task_id,
                                        0x8000_0000 + idx)

    def put_serialized(self, ser: serialization.SerializedObject,
                       object_id: ObjectID | None = None) -> ObjectRef:
        oid = object_id or self._next_put_id()
        if ser.contained_refs:
            with self._ref_lock:  # nested refs live while the object does
                self._pin_locked(ser.contained_refs)
                self._contained_pins.setdefault(oid, []).extend(
                    ser.contained_refs)
        nbytes = ser.payload_nbytes()
        if nbytes <= global_config().max_inline_object_size:
            self.memory.put(oid, "inline", ser.to_payload())
        else:
            self._write_plasma(oid, ser)
            self.memory.put(oid, "plasma", nbytes)
        return ObjectRef(oid, owner_address=self.address)

    def put(self, value: Any) -> ObjectRef:
        return self.put_serialized(serialization.serialize(value))

    def _write_plasma(self, oid: ObjectID,
                      ser: serialization.SerializedObject):
        """Zero-copy produce: grant a write window in the node's arena
        and serialize straight into shared memory — the value's buffers
        are copied exactly once end-to-end (plasma create→seal; falls
        back to a tmp file when the native arena is unavailable)."""
        size = ser.payload_nbytes()
        # Attribution riding the seal (additive keys): the directory
        # learns who produced the object, so `art memory` can name the
        # owner — and, behind the record_object_callsite knob, where in
        # user code the put happened.
        seal_extra: dict = {"owner": self.address}
        if global_config().record_object_callsite:
            callsite = _creation_callsite()
            if callsite:
                seal_extra["callsite"] = callsite
        deadline = time.monotonic() + 60
        while True:
            grant = self._node.call("CreateBuffer",
                                    {"object_id": oid, "size": size},
                                    timeout=60)
            if grant.get("offset") is not None:
                view = self._arena_client.view(grant["path"], grant["offset"],
                                               size)
                ser.write_into(view)
                self._node.call("SealBuffer",
                                {"object_id": oid, **seal_extra},
                                timeout=60)
                return
            if grant.get("exists"):
                return  # idempotent re-put
            if grant.get("busy"):
                # Another producer/pull holds a live grant for this id —
                # it will seal the identical payload; wait for it.
                if time.monotonic() >= deadline:
                    raise exceptions.ObjectLostError(
                        oid, "timed out waiting on a concurrent producer")
                time.sleep(0.02)
                continue
            break
        tmp = os.path.join(self.store_dir,
                           f"{oid.hex()}.tmp.{uuid.uuid4().hex[:8]}")
        with open(tmp, "wb") as f:
            f.write(ser.to_payload())
        self._node.call("SealObject",
                        {"object_id": oid, "tmp_path": tmp, **seal_extra},
                        timeout=60)

    async def _handle_get_object(self, payload):
        """Owner-side object serving for borrowers."""
        oid = payload["object_id"]
        timeout = payload.get("timeout")
        if not self.memory.is_owned(oid):
            return ("unknown", None)
        try:
            kind, value = await self.memory.wait_async(oid, timeout)
        except asyncio.TimeoutError:
            return ("pending", None)
        return (kind, value)

    async def _handle_get_object_status(self, payload):
        return self._status_of(payload["object_id"])

    def _status_of(self, oid: ObjectID) -> str:
        entry = self.memory.get_entry(oid)
        if entry is None:
            return "unknown"
        return "ready" if entry[0] != "pending" else "pending"

    async def _handle_get_object_status_batch(self, payload):
        """One status round trip for a whole batch of refs — waiting on
        N borrowed refs of one owner costs one RPC per round, not N."""
        return {oid: self._status_of(oid)
                for oid in payload["object_ids"]}

    async def _handle_wait_objects(self, payload):
        """Push-based wait: park the reply until ``num_ready`` of the
        listed refs are terminal (ready/error/unknown) or the deadline
        fires, then reply with every ref's status.  The park rides the
        memory store's any-change subscription — no per-ref futures, so
        a 1k-ref wait costs one parked reply and O(refs) dict lookups
        per terminal event."""
        oids = payload["object_ids"]
        num_ready = max(1, int(payload.get("num_ready", 1)))
        # Server-side park is bounded: clients re-issue long-polls, so a
        # forgotten wait can never wedge a reply slot for minutes.
        timeout = min(float(payload.get("timeout", 10.0)), 60.0)
        deadline = time.monotonic() + max(0.0, timeout)
        while True:
            # Register the wakeup BEFORE snapshotting: a put landing
            # from another thread in between then resolves the already-
            # registered future instead of being missed for a full park.
            change = self.memory.change_future()
            statuses = {oid: self._status_of(oid) for oid in oids}
            n_terminal = sum(1 for s in statuses.values()
                             if s != "pending")
            remaining = deadline - time.monotonic()
            if n_terminal >= min(num_ready, len(oids)) or remaining <= 0:
                self.memory.discard_change_future(change)
                return statuses
            await self.memory.wait_change(remaining, change)

    async def _handle_get_object_info(self, payload):
        """Status + payload size in one round trip — the Data engine's
        byte-budgeted backpressure asks owners for completed block sizes
        (ref: BlockMetadata.size_bytes driving the streaming executor's
        resource manager, data/_internal/execution/resource_manager.py)."""
        entry = self.memory.get_entry(payload["object_id"])
        if entry is None:
            return {"status": "unknown", "size": None}
        if entry[0] == "pending":
            return {"status": "pending", "size": None}
        return {"status": "ready", "size": self._entry_nbytes(entry)}

    async def _handle_get_owned_ref_info(self, payload):
        """Owner-side refcounts for the memory-attribution leak scan
        (`art memory`): for each id, the live Python refs, borrower
        count, and in-flight task-arg pins this owner tracks.  ``None``
        means the owner holds NO reference state for the id — with the
        object still in the cluster directory, that is a leak
        candidate."""
        out = {}
        with self._ref_lock:
            for hexid in payload.get("object_ids", ()):
                oid = ObjectID.from_hex(hexid)
                counts = {"local_refs": self._local_refs.get(oid, 0),
                          "borrows": self._borrows.get(oid, 0),
                          "pins": self._pins.get(oid, 0)}
                if not any(counts.values()) \
                        and not self.memory.contains(oid):
                    out[hexid] = None
                else:
                    out[hexid] = counts
        return out

    @staticmethod
    def _entry_nbytes(entry: tuple) -> int | None:
        kind, value = entry
        if kind == "plasma":
            return value
        try:
            return (len(value) if isinstance(value, (bytes, bytearray,
                                                     memoryview))
                    else None)
        except Exception:  # noqa: BLE001
            return None

    def object_sizes(self, refs) -> list:
        """Best-effort payload size per ref (None when pending/unknown).
        Owned refs answer from the memory store; borrowed refs ask the
        owner.  Never blocks on a pending object."""
        async def _one(ref: ObjectRef):
            if self.memory.is_owned(ref.id):
                entry = self.memory.get_entry(ref.id)
                if entry is None or entry[0] == "pending":
                    return None
                return self._entry_nbytes(entry)
            try:
                info = await self._clients.get(ref.owner_address).call_async(
                    "GetObjectInfo", {"object_id": ref.id}, timeout=5)
            except Exception:  # noqa: BLE001 — owner unreachable: unknown
                return None
            return info.get("size")

        async def _gather():
            return await asyncio.gather(*[_one(r) for r in refs])

        return self._io.run_coro(_gather())

    def _deserialize_payload(self, payload, pin_owner=None) -> Any:
        ser = serialization.SerializedObject.from_payload(
            payload, pin_owner=pin_owner)
        return serialization.deserialize(ser)

    def _make_pin_release(self, oid: ObjectID, token):
        """ReadDone sender for a zero-copy get pin; safe from GC/finalizer
        context on any thread (hops to the io loop)."""
        node = self._node
        loop = self._io.loop

        def _release():
            try:
                loop.call_soon_threadsafe(
                    _spawn,
                    node.oneway_async("ReadDone", {"object_id": oid,
                                                   "pin_token": token}))
            except Exception:  # noqa: BLE001 — interpreter shutdown
                pass

        return _release

    async def _pin_renew_loop(self):
        """Heartbeat renewing the daemon-side leases of all live
        zero-copy pins in one batched RPC.  The lease TTL only bounds
        how long a *crashed* reader can wedge an arena slot; live
        readers renew at TTL/3 so a deserialized array held for hours
        stays backed."""
        # This task was spawned from inside a (possibly traced) get()
        # coroutine and inherited its context copy — clear the trace
        # var or every renew heartbeat for the life of the process
        # would record spans attributed to one long-finished request.
        tracing_plane.set_current(None)
        while not self._shutdown:
            ttl = global_config().zero_copy_pin_ttl_s
            await asyncio.sleep(max(0.05, ttl / 3.0))
            pins = [(p.oid, p.token) for p in list(self._live_pins)]
            if not pins:
                continue
            try:
                reply = await self._node.call_async(
                    "RenewPins", {"pins": pins, "ttl": ttl}, timeout=30)
            except Exception:  # noqa: BLE001 — daemon restarting
                continue
            live = {(p.oid, p.token) for p in list(self._live_pins)}
            for oid, token in reply.get("gone", ()):
                if (oid, token) not in live:
                    continue  # value was GC'd mid-heartbeat: benign race
                # The daemon reaped a pin we still hold a value for —
                # its bytes may be recycled under the live view.  This
                # only happens when this process stalls for >TTL (GIL
                # hog, SIGSTOP, swap); make it loud, it's a correctness
                # hazard the user must know about.
                logger.error(
                    "zero-copy pin on %s (token %s) expired at the node "
                    "daemon while the deserialized value is still live; "
                    "its memory may be recycled — copy values you hold "
                    "across long stalls, or raise "
                    "ART_ZERO_COPY_PIN_TTL_S", oid.hex()[:12], token)

    async def _fetch_plasma(self, oid: ObjectID,
                            timeout: float | None) -> tuple:
        """Make the object's payload readable locally.  Returns
        (buffer, pin_owner): arena hits are ZERO-COPY views into shared
        memory, pinned at the daemon until the deserialized value is
        GC'd (ref: plasma-backed read-only arrays — ray.get of a numpy
        array returns a view over shm, not a copy)."""
        payload = {"object_id": oid,
                   "timeout": timeout if timeout else 60.0,
                   "fail_fast_after": global_config().pull_no_holders_grace_s,
                   "pin_ttl": global_config().zero_copy_pin_ttl_s}
        # Inside a sampled trace (caller context rides into this get()
        # coroutine) the daemon records the pull as a child span — the
        # client side is covered by the generic rpc:EnsureLocal span.
        trace = tracing_plane.current_sampled()
        if trace is not None:
            payload["trace"] = trace.to_wire()
        reply = await self._node.call_async("EnsureLocal", payload,
                                            timeout=-1)
        if reply.get("no_holders"):
            raise _AllCopiesLost(oid)
        if reply.get("timeout"):
            raise exceptions.GetTimeoutError(
                f"object {oid.hex()[:12]} not available in time")
        if reply.get("offset") is not None:
            view = self._arena_client.view(
                reply["path"], reply["offset"], reply["size"])
            if reply.get("pinned"):
                token = reply.get("pin_token")
                pin = _ArenaPin(self._make_pin_release(oid, token),
                                oid, token)
                self._live_pins.add(pin)
                if not self._pin_renewer_started:
                    self._pin_renewer_started = True
                    _spawn(self._pin_renew_loop())
                return memoryview(view), pin
            # Unpinned arena window (shouldn't happen): copy out for
            # safety — the slot could be recycled under us.
            return memoryview(bytes(view)), None
        # File-per-object fallback: the mmap stays valid after unlink
        # (POSIX), so plain zero-copy views are already safe.
        return open_object(reply["path"]), None

    async def _get_one(self, ref: ObjectRef, timeout: float | None):
        """Resolve one ref to (kind, data): kind ∈ value|error.

        The outer loop exists for lineage recovery: after a
        reconstruction round the entry is re-resolved from scratch, so a
        replay that *errored* surfaces the task error instead of chasing
        a plasma object that will never reappear."""
        oid = ref.id
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        for _round in range(4):
            remaining = (None if deadline is None
                         else max(0.1, deadline - time.monotonic()))
            if self.memory.is_owned(oid):
                try:
                    kind, value = await self.memory.wait_async(oid, remaining)
                except asyncio.TimeoutError as e:
                    raise exceptions.GetTimeoutError(
                        f"get() timed out on {oid.hex()[:12]}") from e
            else:
                owner = self._clients.get(ref.owner_address)
                kind, value = await owner.call_async(
                    "GetObject", {"object_id": oid, "timeout": remaining},
                    timeout=-1 if remaining is None else remaining + 5)
                if kind == "pending":
                    raise exceptions.GetTimeoutError(
                        f"get() timed out on {oid.hex()[:12]}")
                if kind == "unknown":
                    raise exceptions.ObjectLostError(
                        oid, f"owner {ref.owner_address} does not know "
                        "this object")
            if kind == "plasma":
                try:
                    view, pin_owner = await self._fetch_plasma(
                        oid, remaining)
                except _AllCopiesLost:
                    if not await self._maybe_reconstruct(ref, remaining):
                        raise exceptions.ObjectLostError(
                            oid, "all copies were lost and the object has "
                            "no lineage to reconstruct from") from None
                    if deadline is not None and \
                            time.monotonic() >= deadline:
                        raise exceptions.GetTimeoutError(
                            f"get() timed out on {oid.hex()[:12]} during "
                            "reconstruction") from None
                    continue  # re-resolve: replay may have stored an error
                return ("value",
                        self._deserialize_payload(view, pin_owner))
            if kind == "inline":
                return ("value", self._deserialize_payload(value))
            if kind == "error":
                return ("error", self._deserialize_payload(value))
            raise AssertionError(f"unexpected entry kind {kind}")
        raise exceptions.ObjectLostError(
            oid, "object kept disappearing despite reconstruction")

    def get(self, refs: Sequence[ObjectRef], timeout: float | None) -> list:
        async def _gather():
            return await asyncio.gather(
                *[self._get_one(r, timeout) for r in refs])

        with self._blocked():
            results = self._io.run_coro(_gather())
        out = []
        for kind, data in results:
            if kind == "error":
                raise data
            out.append(data)
        return out

    def wait(self, refs, num_returns, timeout, fetch_local):
        """Block until `num_returns` refs are terminal or `timeout`
        elapses (ref: CoreWorker::Wait — a real blocking wait, not a
        status poll; timeout=0 degrades to a poll).

        Owned refs resolve with synchronous memory-store lookups first
        (an all-ready wait over 1k refs costs zero tasks and zero
        RPCs); only still-pending owned refs park on the store.
        Borrowed refs are grouped BY OWNER: one pump per owner drives a
        ``WaitObjects`` long-poll (the owner parks the reply until a
        listed ref turns terminal), falling back to batched
        ``GetObjectStatusBatch`` polling against peers that predate the
        push path — O(owners) RPCs in flight, never O(refs x polls)."""
        # Sync fast path: classify every ref without touching the loop.
        ready_idx: set[int] = set()
        owned_pending: list[tuple[int, ObjectID]] = []
        by_owner: dict[str, list[tuple[int, ObjectID]]] = {}
        for i, ref in enumerate(refs):
            if self.memory.is_owned(ref.id):
                entry = self.memory.get_entry(ref.id)
                if entry is not None and entry[0] != "pending":
                    ready_idx.add(i)
                else:
                    owned_pending.append((i, ref.id))
            else:
                by_owner.setdefault(ref.owner_address, []).append(
                    (i, ref.id))
        if len(ready_idx) >= num_returns:
            ready = [r for i, r in enumerate(refs) if i in ready_idx]
            not_ready = [r for i, r in enumerate(refs)
                         if i not in ready_idx]
            return ready, not_ready

        async def _status_round():
            # Poll semantics (timeout<=0): one batched status round per
            # owner (the RPCs still complete — timeout=0 bounds
            # *waiting*, not the status check itself).
            async def one_owner(owner_addr, items):
                owner = self._clients.get(owner_addr)
                try:
                    statuses = await owner.call_async(
                        "GetObjectStatusBatch",
                        {"object_ids": [oid for _i, oid in items]},
                        timeout=5)
                except Exception:  # noqa: BLE001 — owner gone: ready(err)
                    for i, _oid in items:
                        ready_idx.add(i)
                    return
                for i, oid in items:
                    if statuses.get(oid, "unknown") != "pending":
                        ready_idx.add(i)

            await asyncio.gather(*[one_owner(a, items)
                                   for a, items in by_owner.items()])

        async def _gather():
            if timeout is not None and timeout <= 0:
                await _status_round()
                return
            progress = asyncio.Event()

            def mark(i: int):
                ready_idx.add(i)
                progress.set()

            tasks = [asyncio.ensure_future(
                self._wait_owned(oid, i, mark))
                for i, oid in owned_pending]
            tasks += [asyncio.ensure_future(
                self._wait_owner_pump(owner_addr, items, mark))
                for owner_addr, items in by_owner.items()]
            deadline = (None if timeout is None
                        else self._io.loop.time() + timeout)
            try:
                while len(ready_idx) < num_returns and \
                        not all(t.done() for t in tasks):
                    remaining = (None if deadline is None else
                                 deadline - self._io.loop.time())
                    if remaining is not None and remaining <= 0:
                        return
                    progress.clear()
                    try:
                        await asyncio.wait_for(progress.wait(), remaining)
                    except asyncio.TimeoutError:
                        return
            finally:
                for t in tasks:
                    t.cancel()

        with self._blocked():
            self._io.run_coro(_gather())
        # Snapshot once: cancelled pumps may still mark() on the io
        # thread; reading the live set twice could drop a ref from
        # BOTH lists (lost forever by wait-loop callers).
        done_idx = set(ready_idx)
        ready = [r for i, r in enumerate(refs) if i in done_idx]
        not_ready = [r for i, r in enumerate(refs) if i not in done_idx]
        return ready, not_ready

    async def _wait_owned(self, oid: ObjectID, index: int, mark):
        await self.memory.wait_async(oid)
        mark(index)

    async def _wait_owner_pump(self, owner_addr: str, items, mark):
        """Drive one owner's borrowed refs to terminal: WaitObjects
        long-polls while the owner supports them (server-side park, no
        client sleeps), batched status polling with backoff otherwise.
        An unreachable owner marks everything terminal — the follow-up
        get() surfaces the real error, same as the old per-ref path."""
        owner = self._clients.get(owner_addr)
        # oid -> ALL indices waiting on it (the same borrowed ref may
        # appear several times in one wait call).
        pending: dict = {}
        for i, oid in items:
            pending.setdefault(oid, []).append(i)
        use_push = True
        delay = 0.005
        while pending:
            oids = list(pending)
            try:
                if use_push:
                    try:
                        statuses = await owner.call_async(
                            "WaitObjects",
                            {"object_ids": oids, "num_ready": 1,
                             "timeout": 10.0}, timeout=20)
                    except RpcError as e:
                        if "no route" not in str(e):
                            raise
                        # Owner predates the push path: poll fallback.
                        use_push = False
                        continue
                else:
                    statuses = await owner.call_async(
                        "GetObjectStatusBatch", {"object_ids": oids},
                        timeout=5)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — owner gone: ready(err)
                for indices in pending.values():
                    for i in indices:
                        mark(i)
                return
            for oid, status in statuses.items():
                if status != "pending" and oid in pending:
                    for i in pending.pop(oid):
                        mark(i)
            if not use_push and pending:
                await asyncio.sleep(delay)
                delay = min(delay * 2, 0.1)

    def _blocked(self):
        """Tell the node daemon this worker is blocked so its cpu can be
        re-used (deadlock avoidance for nested tasks)."""
        return _BlockedCtx(self)

    # ------------------------------------------------------------ tracing

    def _trace_attach(self, spec: TaskSpec) -> None:
        """Stamp the submission's trace context onto the spec.

        Driver submissions with no ambient context are an INGRESS: a
        root context is minted here (head-sampled — the unsampled mint
        is a coin flip and two random ids, well under the 2 µs budget).
        Worker submissions propagate the executing task's context, so a
        serve request's nested tasks stay in its trace.  Only SAMPLED
        contexts ride the wire — the unsampled common case adds zero
        bytes to the frame and zero work downstream."""
        trace = tracing_plane.current()
        if trace is None:
            if self.role != "driver":
                return
            # Hot-path mint: coin first, ids only on a sampling hit —
            # the unsampled .remote() pays one RNG draw here.
            trace = tracing_plane.maybe_mint()
            if trace is None:
                return
        if not trace.sampled:
            return
        call = trace.child()
        spec.trace_ctx = call.to_wire()
        # Driver-local timing attrs: never pickled (TaskSpec.__reduce__
        # is positional), consumed by _trace_task_reply.
        spec._parent_span = trace.span_id
        spec._t_wall = time.time()
        spec._t_submit = time.perf_counter()

    def _trace_task_reply(self, spec: TaskSpec, error: bool = False):
        """Record the client-side call span when a traced task's reply
        (or terminal error) lands: queue = submit → frame write, wire =
        frame write → reply stored."""
        wire = spec.trace_ctx
        t0 = getattr(spec, "_t_submit", None)
        if wire is None or t0 is None:
            return
        now = time.perf_counter()
        t_send = getattr(spec, "_t_send", now)
        stages = {"queue": max(0.0, t_send - t0),
                  "wire": max(0.0, now - t_send)}
        tracing_plane.record_span(
            wire, f"call:{spec.function_name}",
            ts=getattr(spec, "_t_wall", time.time()), dur_s=now - t0,
            stages=stages,
            attrs={"task_id": spec.task_id.hex(),
                   "attempt": spec.attempt},
            error=error, span_id=wire[1],
            parent_id=getattr(spec, "_parent_span", ""),
            service="submitter")
        tracing_plane.record_rpc(
            "PushTask", {"client_queue": stages["queue"],
                         "client_wire": stages["wire"]}, wire[0])

    # ------------------------------------------------------------ tasks

    def submit_task(self, remote_function, args, kwargs, options: TaskOptions):
        fn_key = self.export(remote_function.function, "fn")
        task_id = TaskID.for_normal_task(self.job_id)
        streaming = options.num_returns == "streaming"
        num_returns = -1 if streaming else options.num_returns
        return_refs = []
        if streaming:
            self._register_stream(task_id)
        else:
            for i in range(num_returns):
                oid = ObjectID.for_task_return(task_id, i)
                self.memory.mark_pending(oid)
                return_refs.append(
                    ObjectRef(oid, owner_address=self.address))

        args_payload, pinned = self._pack_args(args, kwargs)
        cfg = global_config()
        spec = TaskSpec(
            task_id=task_id,
            function_id=fn_key,
            function_name=remote_function.function_name,
            args_payload=args_payload,
            num_returns=num_returns,
            owner_address=self.address,
            resources=options.resource_demand(),
            # Streaming tasks never retry: replaying would re-emit items
            # the consumer already observed (ref: generator tasks are
            # non-retriable by default).
            max_retries=(0 if streaming else
                         (options.max_retries
                          if options.max_retries is not None
                          else cfg.task_max_retries_default)),
            retry_exceptions=options.retry_exceptions,
            placement_group_id=(options.placement_group.id
                                if options.placement_group is not None
                                else None),
            placement_group_bundle_index=max(
                options.placement_group_bundle_index, 0),
            runtime_env=self._package_runtime_env(options.runtime_env),
            label_selector=options.label_selector,
            scheduling_strategy=strategy_wire(
                options.scheduling_strategy),
        )
        self._trace_attach(spec)
        if cfg.enable_insight:
            from ant_ray_tpu.util import insight  # noqa: PLC0415

            insight.record_call_submit(spec.function_name,
                                       task_id.hex(), self.role)
        if cfg.enable_task_events:
            task_events.record(task_id.hex(), spec.function_name,
                               "submitted")
        self._post_submit(self._enqueue_task, spec, pinned, 0)
        if streaming:
            from ant_ray_tpu.object_ref import ObjectRefGenerator  # noqa: PLC0415

            return ObjectRefGenerator(task_id, self)
        return return_refs[0] if num_returns == 1 else return_refs

    def _pack_args(self, args, kwargs) -> tuple[bytes, list]:
        """Serialize task args; large blobs are promoted to plasma so the
        control-plane RPC frame stays small (ref behavior:
        max_direct_call_object_size).  Returns (wire payload, refs pinned
        for the task's lifetime — unpinned by the caller on completion)."""
        if not args and not kwargs:
            return _EMPTY_ARGS_PAYLOAD, []
        ser = serialization.serialize((args, kwargs))
        if ser.payload_nbytes() <= global_config().max_inline_object_size:
            if ser.contained_refs:
                self._pin(ser.contained_refs)
            return ser.to_payload(), list(ser.contained_refs)
        # put_serialized() pins the contained refs for the plasma object's
        # lifetime; the task pins only the promoted object itself.
        args_ref = self.put_serialized(ser)
        self._pin([args_ref])
        wrapper = serialization.serialize(PromotedArgs(args_ref))
        return wrapper.to_payload(), [args_ref]

    def _package_runtime_env(self, runtime_env: dict | None):
        """Stage a runtime env into GCS KV (cached per content)."""
        if not runtime_env:
            return None
        from ant_ray_tpu._private import runtime_env as renv  # noqa: PLC0415

        cache_key = renv.content_fingerprint(runtime_env)
        wire = self._renv_cache.get(cache_key)
        if wire is None:
            wire = renv.package(
                runtime_env,
                lambda key, blob: self._gcs.call(
                    "KVPut", {"key": key, "value": blob,
                              "overwrite": False}, retries=3))
            self._renv_cache[cache_key] = wire
        return wire

    def _post_submit(self, fn, *args) -> None:
        """Run fn(*args) on the io loop, coalescing wakeups across a
        burst of submissions from app threads.  The flag is cleared
        before draining, so an append racing the drain at worst costs a
        redundant (harmless) wakeup, never a lost one."""
        self._submit_inbox.append((fn, args))
        if not self._inbox_scheduled:
            self._inbox_scheduled = True
            self._io.loop.call_soon_threadsafe(self._drain_submit_inbox)

    def _drain_submit_inbox(self) -> None:
        self._inbox_scheduled = False
        # One drain callback serves a whole burst of submissions from
        # DIFFERENT app threads, but call_soon_threadsafe copied only
        # the scheduling thread's context — clear the trace contextvar
        # so io-loop machinery (lease acquisition, senders) never
        # attributes its RPCs to whichever thread happened to schedule
        # the wakeup.  Per-task attribution rides spec.trace_ctx.
        tracing_plane.set_current(None)
        inbox = self._submit_inbox
        while inbox:
            fn, args = inbox.popleft()
            try:
                fn(*args)
            except Exception:  # noqa: BLE001 — never kill the drainer
                logger.exception("submission handling failed")

    # ----------------------------------------- scheduling-key submission
    # (ref: NormalTaskSubmitter, task_submission/normal_task_submitter.cc:185
    #  — worker leases are keyed by the task's scheduling class and reused
    #  across queued tasks, with pipelined pushes hiding the RPC round
    #  trip; without this every task pays lease+push+return round trips.)

    def _sched_key(self, spec: TaskSpec) -> tuple:
        from ant_ray_tpu._private import runtime_env as renv  # noqa: PLC0415

        strategy = spec.scheduling_strategy
        return (
            tuple(sorted(spec.resources.items())),
            renv.env_key(spec.runtime_env),
            tuple(sorted((spec.label_selector or {}).items())),
            (spec.placement_group_id, spec.placement_group_bundle_index)
            if spec.placement_group_id is not None else None,
            (tuple(sorted(strategy.items()))
             if isinstance(strategy, dict) else strategy),
        )

    def _enqueue_task(self, spec: TaskSpec, pinned, attempt: int):
        """Queue a task under its scheduling key (io-loop only)."""
        key = self._sched_key(spec)
        state = self._sched_states.get(key)
        if state is None:
            state = _SchedKeyState(
                resources=spec.resources,
                runtime_env=spec.runtime_env,
                label_selector=spec.label_selector,
                pg=((spec.placement_group_id,
                     spec.placement_group_bundle_index)
                    if spec.placement_group_id is not None else None),
                strategy=spec.scheduling_strategy)
            self._sched_states[key] = state
        state.queue.append((spec, pinned, attempt))
        state.wakeup.set()
        self._maybe_acquire(key, state)

    def _maybe_acquire(self, key: tuple, state: _SchedKeyState):
        # Leases scale with queued tasks that IDLE capacity can't absorb:
        # a worker mid-task is not capacity, so a task submitted while
        # the key's only worker executes gets its own lease instead of
        # serializing behind it (ref: NormalTaskSubmitter grows pending
        # lease requests with the task queue, not the lease count).
        # A queue surplus is requested as BATCHED leases: one LeaseWorker
        # round trip asks for up to lease_batch_size workers (acquiring
        # counts requested WORKERS, and the cap bounds them the same
        # way it bounded one-per-request leases).
        cfg = global_config()
        cap = cfg.max_pending_lease_requests
        batch = max(1, cfg.lease_batch_size)
        while (state.acquiring < cap
               and (state.acquiring + max(0, state.workers - state.busy)
                    < len(state.queue))):
            deficit = len(state.queue) - state.acquiring \
                - max(0, state.workers - state.busy)
            want = max(1, min(batch, deficit, cap - state.acquiring))
            state.acquiring += want
            # _spawn, not bare ensure_future: the lease round trip and
            # the grant drains suspend on socket writes, and a GC'd
            # task would leak the lease (workers count never undone).
            _spawn(self._acquire_worker(key, state, want))

    async def _acquire_worker(self, key: tuple, state: _SchedKeyState,
                              count: int = 1):
        try:
            grants = await self._lease_for_state(state, count)
        except Exception as e:  # noqa: BLE001 — infeasible / saturated
            state.acquiring -= count
            # Only a key with no serving capacity at all fails its queue:
            # with live workers the queue still drains through them.
            if state.workers == 0 and state.acquiring == 0:
                while state.queue:
                    spec, pinned, _attempt = state.queue.popleft()
                    # Per-task error naming: the shared scheduling-key
                    # failure must still say which remote call it sank.
                    self._store_error(spec, exceptions.ArtError(
                        f"task {spec.function_name}: {e}"))
                    self._unpin(pinned)
            return
        state.acquiring -= count
        # Count every grant as a worker BEFORE re-examining the queue:
        # _maybe_acquire reads workers-busy as idle capacity, and the
        # grants below are exactly that until their drains start.
        state.workers += len(grants)
        if len(grants) < count:
            # Under-granted batch (the daemon had fewer idle workers
            # than asked): re-request the unfilled deficit NOW — the
            # pre-batching protocol kept up to cap CONCURRENT lease
            # requests alive, and a crash-recovery burst must not
            # serialize behind this one grant finishing its drain.
            self._maybe_acquire(key, state)
        # Extra grants (batched lease: one daemon round trip served a
        # queue surplus) drain concurrently; grants the queue has
        # already drained past are returned to the daemon immediately.
        for extra in grants[1:]:
            _spawn(self._run_granted(key, state, *extra))
        await self._run_granted(key, state, *grants[0])

    async def _run_granted(self, key: tuple, state: _SchedKeyState,
                           node, worker_addr: str, worker_id):
        """Drain the queue through one granted lease, then return it.
        ``state.workers`` was incremented by the caller (synchronously
        with the grant, so _maybe_acquire never over-leases)."""
        try:
            if state.queue:
                await self._worker_drain(state, worker_addr)
        finally:
            state.workers -= 1
            try:
                await node.call_async(
                    "ReturnWorker", {"worker_id": worker_id}, timeout=10)
            except Exception:  # noqa: BLE001
                pass
            if state.queue:
                self._maybe_acquire(key, state)
            elif (state.workers == 0 and state.acquiring == 0
                  and self._sched_states.get(key) is state):
                del self._sched_states[key]

    async def _lease_for_state(self, state: _SchedKeyState,
                               count: int = 1):
        """Acquire worker leases for a scheduling key, following
        spillback redirects; returns a non-empty list of
        (node_client, worker_addr, worker_id) grants.  ``count > 1``
        asks the serving daemon for a batch in the same round trip
        (payload ``count`` — ignored by pre-batching daemons, which
        reply with the classic single grant).  Raises on terminal
        infeasibility/saturation."""
        lease_payload = {"resources": state.resources,
                         "runtime_env": state.runtime_env,
                         "job_id": self.job_id,
                         # Lessee identity: the daemon reclaims this
                         # lease if the owner dies before ReturnWorker.
                         "owner": self.address,
                         "label_selector": state.label_selector,
                         "strategy": state.strategy}
        if count > 1:
            lease_payload["count"] = count
        if state.queue:
            # Head task's plasma deps ride the lease so the serving node
            # can pull them before the grant (ref:
            # lease_dependency_manager.h pull-before-grant; later tasks
            # pipelined onto the same lease fetch at execution).  ONLY
            # refs known to be plasma-backed qualify: an inline object
            # has no cluster locations, so the daemon's pull would poll
            # an empty holder list for its whole budget and stall every
            # lease of the key (pending and borrowed refs are likewise
            # excluded — their storage class is unknown here).
            deps = [r.id for r in state.queue[0][1]
                    if (entry := self.memory.get_entry(r.id)) is not None
                    and entry[0] == "plasma"]
            if deps:
                lease_payload["deps"] = deps
            # The head task's trace rides the lease so the serving
            # daemon records the grant as a child span of the request.
            head_trace = state.queue[0][0].trace_ctx
            if head_trace is not None:
                lease_payload["trace"] = head_trace
        if state.pg is not None:
            node = await self._resolve_bundle_node(*state.pg)
            lease_payload["pg"] = state.pg
        else:
            node = self._node
        infeasible_deadline: float | None = None
        deadline = time.monotonic() + global_config().lease_retry_deadline_s
        hops = 0
        conn_failures = 0
        while time.monotonic() < deadline:
            hops += 1
            if hops > 4:
                await asyncio.sleep(min(0.05 * (hops - 4), 0.5))
            try:
                reply = await node.call_async(
                    "LeaseWorker", lease_payload, timeout=-1)
            except RpcConnectionError:
                # Transient daemon unavailability (restart, chaos, net
                # blip) must not be terminal for the whole queue — back
                # off and retry within the deadline, falling back to the
                # home node if a spillback target died.
                conn_failures += 1
                self._clients.invalidate(node.address)
                node = (self._node if state.pg is None
                        else await self._resolve_bundle_node(*state.pg))
                # Back on the home node the strategy must re-route from
                # scratch — a stale routed flag would let a hard pin be
                # served wherever we fell back to.
                lease_payload.pop("routed", None)
                await asyncio.sleep(min(0.1 * conn_failures, 2.0))
                continue
            if "granted" in reply:
                grants = [(node, reply["granted"], reply["worker_id"])]
                grants.extend(
                    (node, e["granted"], e["worker_id"])
                    for e in reply.get("extra", ()))
                return grants
            if "spill" in reply:
                node = self._clients.get(reply["spill"])
                if reply.get("routed"):
                    # A strategy redirect already picked this target:
                    # the next daemon serves it instead of re-running
                    # the picker (which would ping-pong).
                    lease_payload = dict(lease_payload, routed=True)
            elif "infeasible" in reply:
                # With a live autoscaler the recorded demand may
                # provision a node — wait and retry instead of failing
                # (ref: infeasible tasks queue until the autoscaler
                # satisfies them).  Without one, fail fast.
                if await self._autoscaling_enabled():
                    if infeasible_deadline is None:
                        infeasible_deadline = time.monotonic() + \
                            global_config().infeasible_wait_s
                        deadline = max(deadline, infeasible_deadline + 1)
                    if time.monotonic() < infeasible_deadline:
                        await asyncio.sleep(1.0)
                        continue
                reason = reply.get("reason") or (
                    f"requests resources {state.resources} that no node "
                    "can ever satisfy")
                raise exceptions.ArtError(f"task is infeasible: {reason}")
            else:
                raise exceptions.ArtError(f"bad lease reply {reply}")
        raise exceptions.ArtError(
            f"tasks requesting {state.resources} could not be scheduled "
            f"within {global_config().lease_retry_deadline_s:.0f}s "
            f"({hops} spillback hops) — cluster saturated or demand "
            "unsatisfiable")

    async def _worker_drain(self, state: _SchedKeyState, worker_addr: str):
        """Feed queued tasks of one scheduling key to one leased worker,
        keeping up to pipeline_depth pushes in flight; the lease lingers
        briefly on an empty queue so sync call→get loops reuse it."""
        cfg = global_config()
        client = self._clients.get(worker_addr)
        depth = max(1, cfg.task_push_pipeline_depth)
        linger = cfg.task_lease_linger_s
        marked_busy = False

        def _set_busy(value: bool):
            nonlocal marked_busy
            if value and not marked_busy:
                marked_busy = True
                state.busy += 1
            elif not value and marked_busy:
                marked_busy = False
                state.busy -= 1

        try:
            await self._worker_drain_loop(
                state, client, depth, linger, _set_busy)
        finally:
            _set_busy(False)

    async def _worker_drain_loop(self, state, client, depth, linger,
                                 _set_busy):
        inflight: deque = deque()
        dead: Exception | None = None
        while True:
            # Pipeline beyond one in-flight task only for queue surplus
            # that pending lease acquisitions could not absorb anyway —
            # greedily batching into one worker would serialize tasks
            # that parallel workers should run.
            while (dead is None and state.queue and len(inflight) < depth
                   and (not inflight
                        or len(state.queue) > state.acquiring)):
                spec, pinned, attempt = state.queue.popleft()
                spec.attempt = attempt
                if spec.trace_ctx is not None:
                    spec._t_send = time.perf_counter()
                fut = client.try_send_deferred("PushTask", spec)
                if fut is None:
                    try:
                        fut = await client.send_request("PushTask", spec,
                                                        defer=True)
                    except (RpcConnectionError, OSError) as e:
                        dead = e
                        state.queue.appendleft((spec, pinned, attempt))
                        # Frames deferred earlier this burst were never
                        # shipped — fail their futures (reaped below as
                        # retries) rather than leaving them to replay.
                        client.discard_deferred()
                        break
                inflight.append((spec, pinned, attempt, fut))
            # A worker with pushes in flight is busy — not idle capacity
            # — so _maybe_acquire leases more workers for queue surplus.
            _set_busy(bool(inflight))
            if dead is None and inflight:
                try:
                    await client.flush_deferred()
                except (RpcConnectionError, OSError) as e:
                    dead = e
            if inflight:
                spec, pinned, attempt, fut = inflight.popleft()
                try:
                    reply = await fut
                    self._store_returns(spec, reply["returns"])
                    self._unpin(pinned)
                except (RpcConnectionError, asyncio.CancelledError,
                        exceptions.WorkerCrashedError) as e:
                    dead = (e if isinstance(e, Exception)
                            else exceptions.WorkerCrashedError(repr(e)))
                    self._retry_or_fail(spec, pinned, attempt, dead)
                except exceptions.ArtError as e:
                    self._store_error(spec, e)
                    self._unpin(pinned)
                except Exception as e:  # noqa: BLE001 — never lose a task
                    logger.exception("internal error running task %s",
                                     spec.function_name)
                    self._store_error(spec, exceptions.ArtError(repr(e)))
                    self._unpin(pinned)
                continue
            if dead is not None:
                return
            if state.queue:
                continue
            # Empty queue, nothing in flight: linger for the next task.
            state.wakeup.clear()
            if not state.queue:  # re-check after clear (enqueue races set)
                try:
                    await asyncio.wait_for(state.wakeup.wait(), linger)
                except asyncio.TimeoutError:
                    return
            if not state.queue:
                return

    def _retry_or_fail(self, spec: TaskSpec, pinned, attempt: int,
                       err: Exception):
        """A pushed task's worker died: retry on a fresh lease (bounded
        by max_retries) or surface the error."""
        if attempt < spec.max_retries:
            logger.warning("task %s attempt %d/%d failed: %s",
                           spec.function_name, attempt + 1,
                           spec.max_retries + 1, err)
            # Brief backoff so daemons reap dead workers before the
            # retry leases again (ref: NormalTaskSubmitter retry delays).
            self._io.loop.call_later(
                min(0.05 * (attempt + 1), 0.5),
                self._enqueue_task, spec, pinned, attempt + 1)
        else:
            self._store_error(spec, exceptions.WorkerCrashedError(
                f"task {spec.function_name} failed after "
                f"{spec.max_retries + 1} attempts: {err}"))
            self._unpin(pinned)

    async def _resolve_bundle_node(self, pg_id, bundle_index: int):
        """Wait for the placement group, return the bundle's node client.
        Bundle → node never changes after creation, so resolution is
        cached (no per-task GCS round-trip on the hot path)."""
        cached = self._pg_bundle_cache.get(pg_id)
        if cached is None:
            for _ in range(240):
                state = await self._gcs.call_async(
                    "GetPlacementGroup", {"pg_id": pg_id}, timeout=10)
                if state is None:
                    raise exceptions.ArtError("placement group was removed")
                if state["state"] == "FAILED":
                    raise exceptions.ArtError(
                        f"placement group failed: {state.get('reason', '')}")
                if state["state"] == "CREATED":
                    cached = state["bundle_nodes"]
                    self._pg_bundle_cache[pg_id] = cached
                    break
                await asyncio.sleep(0.25)
            else:
                raise exceptions.ArtError(
                    "placement group never became ready")
        if not 0 <= bundle_index < len(cached):
            raise exceptions.ArtError(
                f"bundle index {bundle_index} out of range for group with "
                f"{len(cached)} bundles")
        return self._clients.get(cached[bundle_index])

    async def _autoscaling_enabled(self) -> bool:
        """Cached (10s) GCS check for a live autoscaler heartbeat."""
        now = time.monotonic()
        cached = getattr(self, "_autoscaling_cache", None)
        if cached is not None and now - cached[1] < 10.0:
            return cached[0]
        try:
            enabled = bool(await self._gcs.call_async(
                "AutoscalingEnabled", {}, timeout=5))
        except Exception:  # noqa: BLE001 — GCS briefly away: fail fast
            enabled = False
        self._autoscaling_cache = (enabled, now)
        return enabled

    async def _lease_and_push(self, spec: TaskSpec) -> dict:
        """Lease a worker (following spillback redirects), push the task,
        return the worker reply (ref: NormalTaskSubmitter::SubmitTask)."""
        lease_payload = {"resources": spec.resources,
                         "runtime_env": spec.runtime_env,
                         "job_id": self.job_id,
                         "label_selector": spec.label_selector,
                         "strategy": spec.scheduling_strategy}
        if spec.placement_group_id is not None:
            node = await self._resolve_bundle_node(
                spec.placement_group_id, spec.placement_group_bundle_index)
            lease_payload["pg"] = (spec.placement_group_id,
                                   spec.placement_group_bundle_index)
        else:
            node = self._node
        infeasible_deadline: float | None = None
        # Spillback is redirect-following, not a retry budget: on a
        # saturated cluster two busy nodes legitimately bounce a lease
        # between each other until capacity frees (the reference's
        # submitter follows retry_at_raylet_address unboundedly,
        # normal_task_submitter.cc:435).  Bound by TIME, not hops, and
        # back off as the bounce count grows so the ping-pong doesn't
        # melt the control plane.
        deadline = time.monotonic() + global_config().lease_retry_deadline_s
        hops = 0
        while time.monotonic() < deadline:
            hops += 1
            if hops > 4:
                await asyncio.sleep(min(0.05 * (hops - 4), 0.5))
            reply = await node.call_async(
                "LeaseWorker", lease_payload, timeout=-1)
            if "granted" in reply:
                worker_addr = reply["granted"]
                worker_id = reply["worker_id"]
                worker = self._clients.get(worker_addr)
                try:
                    return await worker.call_async("PushTask", spec,
                                                   timeout=-1)
                finally:
                    try:
                        await node.call_async(
                            "ReturnWorker", {"worker_id": worker_id},
                            timeout=10)
                    except Exception:  # noqa: BLE001
                        pass
            elif "spill" in reply:
                node = self._clients.get(reply["spill"])
                if reply.get("routed"):
                    lease_payload = dict(lease_payload, routed=True)
            elif "infeasible" in reply:
                # With a live autoscaler the recorded demand may
                # provision a node — wait and retry instead of failing
                # (ref: infeasible tasks queue until the autoscaler
                # satisfies them).  Without one, fail fast as before.
                if await self._autoscaling_enabled():
                    if infeasible_deadline is None:
                        infeasible_deadline = time.monotonic() + \
                            global_config().infeasible_wait_s
                        # Provisioning may take longer than the lease
                        # deadline — an infeasible wait extends it.
                        deadline = max(deadline, infeasible_deadline + 1)
                    if time.monotonic() < infeasible_deadline:
                        await asyncio.sleep(1.0)
                        continue
                reason = reply.get("reason") or (
                    f"requests resources {spec.resources} that no node "
                    "can ever satisfy")
                raise exceptions.ArtError(
                    f"task {spec.function_name} is infeasible: {reason}")
            else:
                raise exceptions.ArtError(f"bad lease reply {reply}")
        raise exceptions.ArtError(
            f"task {spec.function_name} could not be scheduled within "
            f"{global_config().lease_retry_deadline_s:.0f}s "
            f"({hops} spillback hops) — cluster saturated or demand "
            f"unsatisfiable")

    # --------------------------------------------------- streaming returns

    async def _handle_stream_item(self, payload):
        """A streaming task produced its next item (worker → owner,
        ordered oneway on one connection)."""
        task_id = payload["task_id"]
        oid = ObjectID.for_task_return(task_id, payload["index"])
        if task_id in self._released_streams:
            # The consumer abandoned this stream; drop the item instead
            # of storing it forever (plasma copies are freed explicitly).
            if payload["kind"] == "plasma":
                self._send_oneway(self.gcs_address, "FreeObject",
                                  {"object_id": oid})
            return True
        self.memory.put(oid, payload["kind"], payload["data"])
        state = self._streams.get(task_id)
        if state is not None:
            with state.cond:
                state.received = max(state.received, payload["index"] + 1)
                state.cond.notify_all()
        return True

    def _register_stream(self, task_id: TaskID) -> None:
        self._streams[task_id] = _StreamState()

    def _finish_stream(self, task_id: TaskID, total: int,
                       error: Exception | None) -> None:
        state = self._streams.get(task_id)
        if state is None:
            return
        with state.cond:
            state.total = total
            state.error = error
            state.cond.notify_all()

    def stream_next(self, task_id: TaskID, index: int,
                    timeout: float | None):
        """Block until return #index exists (→ its ObjectRef), the stream
        ends (→ None), or a mid-stream failure surfaces (→ raises).
        A missing stream (already fully consumed / released) reads as
        exhausted, so re-iterating a finished generator raises
        StopIteration like any other iterator."""
        state = self._streams.get(task_id)
        if state is None:
            return None
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with state.cond:
            while True:
                # Items already received stream out even after a failure —
                # the error surfaces at the point production stopped.
                if index < state.received:
                    return ObjectRef(
                        ObjectID.for_task_return(task_id, index),
                        owner_address=self.address)
                if state.total is not None and index >= state.total:
                    # End marker seen AND index past it.  Items travel on
                    # a different connection than the marker, so wait for
                    # stragglers (received < total) instead of dropping
                    # them.
                    if state.error is not None:
                        self._streams.pop(task_id, None)
                        raise state.error
                    if state.received >= state.total:
                        self._streams.pop(task_id, None)
                        return None
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise exceptions.GetTimeoutError(
                        f"stream item {index} of "
                        f"{task_id.hex()[:12]} not ready in time")
                state.cond.wait(remaining if remaining is not None
                                else 1.0)

    def release_stream(self, task_id: TaskID, consumed: int) -> None:
        """Drop an abandoned stream's state and free the items the
        consumer never took (called from ObjectRefGenerator.__del__ —
        without it, a half-read stream leaks its tail forever).  The
        task id is remembered so items still in flight from the
        still-running producer are dropped on arrival."""
        state = self._streams.pop(task_id, None)
        if state is None:
            return
        self._released_streams[task_id] = True
        while len(self._released_streams) > 1024:  # bounded memory
            self._released_streams.pop(
                next(iter(self._released_streams)))
        with state.cond:
            received = state.received
        with self._ref_lock:
            for i in range(consumed, received):
                oid = ObjectID.for_task_return(task_id, i)
                if self.memory.is_owned(oid):
                    self._maybe_free_locked(oid)

    def _store_returns(self, spec: TaskSpec, returns: list):
        if spec.trace_ctx is not None:
            failed = any(
                kind == "error"
                or (kind == "stream_end" and data[1] is not None)
                for kind, data in returns)
            self._trace_task_reply(spec, error=failed)
        if spec.num_returns == -1:  # streaming: end-of-stream marker
            kind, data = returns[0]
            assert kind == "stream_end", kind
            count, err_payload = data
            error = (self._deserialize_payload(err_payload)
                     if err_payload is not None else None)
            self._finish_stream(spec.task_id, count, error)
            return
        for i, (kind, data) in enumerate(returns):
            oid = ObjectID.for_task_return(spec.task_id, i)
            self.memory.put(oid, kind, data)
            # Normal-task plasma returns are reconstructible by lineage;
            # actor-task replay is unsafe (state mutations) so actor
            # returns (function_id == "") are excluded, as are tasks the
            # user marked non-retryable (at-most-once side effects).
            if kind == "plasma" and spec.function_id and spec.max_retries:
                self._lineage[oid] = spec

    # --------------------------------------------------- device objects
    # (ref capability: GPUObjectStore per actor + tensor transports,
    #  experimental/gpu_object_manager/ — here the transport is
    #  host↔HBM DMA + RPC; see experimental/device_objects.py)

    async def _handle_device_tensor_fetch(self, payload):
        array = self._device_objects.get(payload["token"])
        if array is None:
            return None

        def dma_out():
            import numpy as np  # noqa: PLC0415

            # device→host DMA (blocks); the RPC layer pickles the
            # ndarray (protocol 5 handles ml_dtypes like bfloat16)
            return np.asarray(array)

        return await asyncio.get_running_loop().run_in_executor(
            None, dma_out)

    async def _handle_device_tensor_free(self, payload):
        self._device_objects.pop(payload["token"], None)
        return True

    async def _handle_device_tensor_send_via(self, payload):
        """Collective-transport trigger: push the sharded array's
        shards to the requesting consumer over the collective group
        (ref capability: collective_tensor_transport's sender side).
        Replies immediately with whether the token exists — the reply
        is the consumer's go/no-go BEFORE it parks in recv (a missing
        token must surface as ObjectLost, not a recv hang); the sends
        themselves run in an executor, blocking until the consumer's
        recvs match."""
        array = self._device_objects.get(payload["token"])
        if array is None:
            return False
        from ant_ray_tpu.experimental.tensor_transport import (  # noqa: PLC0415
            send_shards,
        )

        asyncio.get_running_loop().run_in_executor(
            None, send_shards, array, payload["dst_rank"],
            payload["group"])
        return True

    def _fetch_device_tensor(self, holder: str, token: str,
                             timeout: float | None):
        client = self._clients.get(holder)
        with self._blocked():
            return self._io.run_coro(client.call_async(
                "DeviceTensorFetch", {"token": token},
                timeout=-1 if timeout is None else timeout))

    def pin_for_grace(self, ref: ObjectRef, grace_s: float = 60.0):
        """Hold an extra pin on an owned object for a grace window —
        covers the gap between returning a ref from a task and the
        consumer's BorrowAdd registration, after which normal
        refcounting governs."""
        oid = ref.id
        with self._ref_lock:
            self._pins[oid] = self._pins.get(oid, 0) + 1

        def _expire():
            with self._ref_lock:
                count = self._pins.get(oid, 0) - 1
                if count <= 0:
                    self._pins.pop(oid, None)
                else:
                    self._pins[oid] = count
                if self.memory.is_owned(oid):
                    self._maybe_free_locked(oid)

        self._io.loop.call_soon_threadsafe(
            self._io.loop.call_later, grace_s, _expire)

    # ------------------------------------------------- lineage recovery

    async def _maybe_reconstruct(self, ref: ObjectRef,
                                 timeout: float | None = None) -> bool:
        """Recover a lost plasma object: owners re-execute the producing
        task; borrowers ask the owner to (bounded by the caller's
        remaining get() timeout)."""
        oid = ref.id
        if self.memory.is_owned(oid):
            return await self._reconstruct_owned(oid)
        try:
            owner = self._clients.get(ref.owner_address)
            return bool(await owner.call_async(
                "ReconstructObject", {"object_id": oid},
                timeout=-1 if timeout is None else timeout + 5))
        except Exception as e:  # noqa: BLE001 — owner gone: unrecoverable
            logger.warning("owner reconstruction RPC for %s failed: %s",
                           oid.hex()[:8], e)
            return False

    async def _handle_reconstruct_object(self, payload):
        oid = payload["object_id"]
        if not self.memory.is_owned(oid):
            return False
        return await self._reconstruct_owned(oid)

    async def _reconstruct_owned(self, oid: ObjectID) -> bool:
        spec = self._lineage.get(oid)
        if spec is None:
            return False
        fut = self._reconstructions.get(spec.task_id)
        if fut is None:
            # One re-execution covers all of the task's return objects;
            # concurrent waiters share it.
            fut = asyncio.ensure_future(self._reexecute_for_lineage(spec))
            self._reconstructions[spec.task_id] = fut
            fut.add_done_callback(
                lambda _f: self._reconstructions.pop(spec.task_id, None))
        try:
            await asyncio.shield(fut)
            return True
        except Exception as e:  # noqa: BLE001
            logger.warning("lineage re-execution of %s failed: %s",
                           spec.function_name, e)
            return False

    async def _reexecute_for_lineage(self, spec: TaskSpec):
        logger.info("reconstructing lost outputs of %s by lineage "
                    "re-execution", spec.function_name)
        last: Exception | None = None
        for _attempt in range(3):
            try:
                reply = await self._lease_and_push(spec)
                self._store_returns(spec, reply["returns"])
                return
            except (RpcConnectionError, exceptions.WorkerCrashedError) as e:
                last = e
        raise exceptions.ObjectLostError(
            ObjectID.for_task_return(spec.task_id, 0),
            f"lineage re-execution kept failing: {last}")

    def _store_error(self, spec: TaskSpec, err: Exception):
        if spec.trace_ctx is not None:
            self._trace_task_reply(spec, error=True)
        if spec.num_returns == -1:  # streaming: fail the stream
            state = self._streams.get(spec.task_id)
            self._finish_stream(
                spec.task_id,
                state.received if state is not None else 0, err)
            return
        payload = serialization.serialize_error(err).to_payload()
        for i in range(spec.num_returns):
            oid = ObjectID.for_task_return(spec.task_id, i)
            self.memory.put(oid, "error", payload)

    # ------------------------------------------------------------ actors

    def create_actor(self, actor_class, args, kwargs, options: ActorOptions):
        from ant_ray_tpu.actor import ActorHandle  # noqa: PLC0415

        declared = set(options.concurrency_groups or ())
        undeclared = {g for g in actor_class.method_concurrency_groups()
                      .values() if g not in declared}
        if undeclared:
            raise ValueError(
                f"Methods of {actor_class._class_name} use concurrency "
                f"group(s) {sorted(undeclared)} not declared in "
                f"concurrency_groups={sorted(declared)} "
                "(ref: @ray.remote(concurrency_groups=...))")
        cls_key = self.export(actor_class.cls, "cls")
        actor_id = ActorID.of(self.job_id)
        ser = serialization.serialize((args, kwargs))
        args_payload = ser.to_payload()
        # Large ctor args travel through plasma like task args do —
        # except for detached actors, whose restarts must outlive this
        # owner process, so their args stay embedded in the GCS spec.
        promote = (options.lifetime != "detached"
                   and len(args_payload)
                   > global_config().max_inline_object_size)
        if promote:
            args_ref = self.put_serialized(ser)
            self._pin([args_ref])
            with self._ref_lock:
                self._actor_ctor_pins[actor_id] = [args_ref]
            args_payload = serialization.serialize(
                PromotedArgs(args_ref)).to_payload()
        elif ser.contained_refs:
            # Constructor args must survive actor restarts; released when
            # the actor is killed or observed permanently dead.
            self._pin(ser.contained_refs)
            with self._ref_lock:
                self._actor_ctor_pins[actor_id] = list(ser.contained_refs)
        cfg = global_config()
        spec = ActorSpec(
            actor_id=actor_id,
            class_id=cls_key,
            class_name=actor_class._class_name,
            args_payload=args_payload,
            owner_address=self.address,
            resources=options.resource_demand(),
            placement_resources=options.placement_demand(),
            max_restarts=(options.max_restarts
                          if options.max_restarts is not None
                          else cfg.actor_max_restarts_default),
            max_concurrency=options.max_concurrency,
            concurrency_groups=options.concurrency_groups,
            name=options.name,
            namespace=options.namespace or "default",
            lifetime=options.lifetime,
            job_id=self.job_id,
            placement_group_id=(options.placement_group.id
                                if options.placement_group is not None
                                else None),
            placement_group_bundle_index=max(
                options.placement_group_bundle_index, 0),
            runtime_env=self._package_runtime_env(options.runtime_env),
            label_selector=options.label_selector,
            scheduling_strategy=strategy_wire(
                options.scheduling_strategy),
        )
        reply = self._gcs.call("CreateActor", spec, retries=3)
        if "error" in reply:
            if options.get_if_exists and options.name:
                return self.get_actor(options.name, options.namespace)
            raise ValueError(reply["error"])
        meta = {
            "method_names": actor_class.method_names(),
            "method_num_returns": actor_class.method_num_returns(),
            "max_task_retries": options.max_task_retries,
            "method_concurrency_groups":
                actor_class.method_concurrency_groups(),
        }
        self._actor_meta_cache[actor_id] = meta
        self._gcs.call("KVPut", {
            "key": f"actor_meta:{actor_id.hex()}",
            "value": serialization.dumps_code(meta)}, retries=3)
        return ActorHandle(actor_id, actor_class._class_name,
                           meta["method_names"],
                           max_concurrency=options.max_concurrency,
                           method_num_returns=meta["method_num_returns"],
                           max_task_retries=options.max_task_retries,
                           method_concurrency_groups=meta[
                               "method_concurrency_groups"])

    def get_actor(self, name: str, namespace: str | None):
        from ant_ray_tpu.actor import ActorHandle  # noqa: PLC0415

        info = self._gcs.call("GetNamedActor", {
            "name": name, "namespace": namespace or "default"}, retries=3)
        if info is None:
            raise ValueError(f"Failed to look up actor {name!r}")
        actor_id = info["actor_id"]
        meta = self._actor_meta_cache.get(actor_id)
        if meta is None:
            blob = self._gcs.call(
                "KVGet", {"key": f"actor_meta:{actor_id.hex()}"}, retries=3)
            meta = serialization.loads_code(blob) if blob else {
                "method_names": (), "method_num_returns": {}}
            self._actor_meta_cache[actor_id] = meta
        return ActorHandle(actor_id, info["class_name"],
                           meta["method_names"],
                           method_num_returns=meta["method_num_returns"],
                           max_task_retries=meta.get("max_task_retries", 0),
                           method_concurrency_groups=meta.get(
                               "method_concurrency_groups", {}))

    def kill_actor(self, handle, no_restart: bool = True):
        self._gcs.call("KillActor", {
            "actor_id": handle.actor_id, "no_restart": no_restart}, retries=3)
        state = self._actor_states.get(handle.actor_id)
        if state is not None:
            state.address = ""
        if no_restart:
            self._release_actor_ctor_pins(handle.actor_id)

    def _release_actor_ctor_pins(self, actor_id):
        """Drop constructor-arg pins once the actor can never restart."""
        with self._ref_lock:
            pins = self._actor_ctor_pins.pop(actor_id, None)
            if pins:
                self._unpin_locked(pins)

    def cancel(self, ref, force=False, recursive=True):
        """Best-effort cancellation of a not-yet-executing ACTOR task.

        A call still queued client-side is failed locally with
        :class:`TaskCancelledError`; one already pushed is dropped
        worker-side if its executor has not started it.  Running tasks
        are never interrupted — user code cannot be preempted safely, so
        layers that need in-flight bounds (Serve) shed at dequeue via
        request deadlines and call this for the queued remainder."""
        task_id = ref.id.task_id()
        actor_id = task_id.actor_id()
        nil_fill = b"\xff" * (ActorID.SIZE - JobID.SIZE)
        if actor_id._bytes[JobID.SIZE:] == nil_fill:
            # Normal (non-actor) task: the lease path has no cancel
            # channel yet; keep the round-1 no-op there.
            logger.warning(
                "cancel() supports actor tasks only; ignoring %s", ref)
            return
        self._post_submit(self._cancel_actor_task, actor_id, task_id)

    def _cancel_actor_task(self, actor_id, task_id) -> None:
        """io-loop only: fail the call locally if still queued, else ask
        the worker to drop it before execution (ordered behind the
        already-shipped PushTask on the same connection)."""
        state = self._actor_states.get(actor_id)
        if state is not None:
            for i, (spec, pinned, _attempt) in enumerate(state.queue):
                if spec.task_id == task_id:
                    del state.queue[i]
                    self._store_error(
                        spec, exceptions.TaskCancelledError(
                            task_id, "cancelled before dispatch"))
                    self._unpin(pinned)
                    return
        address = state.address if state is not None else ""
        if address:
            self._send_oneway(address, "CancelTask", {"task_id": task_id})

    def submit_actor_task(self, handle, method_name, args, kwargs,
                          options: TaskOptions):
        actor_id = handle.actor_id
        task_id = TaskID.for_actor_task(actor_id)
        streaming = options.num_returns == "streaming"
        num_returns = -1 if streaming else options.num_returns
        return_refs = []
        if streaming:
            self._register_stream(task_id)
        else:
            for i in range(num_returns):
                oid = ObjectID.for_task_return(task_id, i)
                self.memory.mark_pending(oid)
                return_refs.append(
                    ObjectRef(oid, owner_address=self.address))

        args_payload, pinned = self._pack_args(args, kwargs)
        spec = TaskSpec(
            task_id=task_id,
            function_id="",
            function_name=f"{handle.class_name}.{method_name}",
            args_payload=args_payload,
            num_returns=num_returns,
            owner_address=self.address,
            resources={},
            max_retries=(0 if streaming else
                         getattr(handle, "_max_task_retries", 0)),
            actor_id=actor_id,
            method_name=method_name,
            concurrency_group=options.concurrency_group,
        )
        self._trace_attach(spec)

        if global_config().enable_task_events:
            task_events.record(task_id.hex(), spec.function_name,
                               "submitted", actor_id=actor_id.hex())

        self._post_submit(self._enqueue_actor_task, actor_id, spec, pinned)
        if streaming:
            from ant_ray_tpu.object_ref import ObjectRefGenerator  # noqa: PLC0415

            return ObjectRefGenerator(task_id, self)
        return return_refs[0] if num_returns == 1 else return_refs

    def _enqueue_actor_task(self, actor_id, spec, pinned) -> None:
        """Queue an actor call in submission order (io-loop only)."""
        state = self._actor_states.get(actor_id)
        if state is None:
            state = _ActorSubmitState(actor_id=actor_id)
            self._actor_states[actor_id] = state
        spec.sequence_no = state.next_seq
        state.next_seq += 1
        state.queue.append((spec, pinned, 0))
        if not state.sender_running:
            state.sender_running = True
            _spawn(self._actor_sender(state))

    @staticmethod
    async def _safe_flush(client):
        """Flush deferred frames; connection errors surface through the
        failed futures' done-callbacks (retry path), not here."""
        if client is None:
            return
        try:
            await client.flush_deferred()
        except (RpcConnectionError, OSError):
            pass

    async def _actor_sender(self, state: _ActorSubmitState):
        """Drains the per-actor queue in order; pipelined deferred sends
        coalesce each burst into one transport write, flushed whenever
        the queue empties, the target changes, or the sender suspends
        (ref: SequentialActorSubmitQueue)."""
        client = None
        try:
            while state.queue:
                spec, pinned, attempt = state.queue.popleft()
                if state.dead_reason is not None:
                    self._store_error(spec, exceptions.ActorDiedError(
                        state.actor_id, state.dead_reason))
                    self._unpin(pinned)
                    continue
                if not state.address:
                    # About to suspend on the GCS — ship what we have.
                    await self._safe_flush(client)
                    info = await self._gcs.call_async("WaitActorAlive", {
                        "actor_id": state.actor_id, "timeout": 120.0,
                    }, timeout=-1)
                    if info is None or info["state"] != ACTOR_ALIVE:
                        reason = (info or {}).get("death_reason",
                                                  "actor not found")
                        state.dead_reason = reason or "failed to start"
                        self._release_actor_ctor_pins(state.actor_id)
                        self._store_error(spec, exceptions.ActorDiedError(
                            state.actor_id, state.dead_reason))
                        self._unpin(pinned)
                        continue
                    state.address = info["address"]
                next_client = self._clients.get(state.address)
                if next_client is not client:
                    await self._safe_flush(client)  # old target first
                    client = next_client
                spec.attempt = attempt
                if spec.trace_ctx is not None:
                    spec._t_send = time.perf_counter()
                # Sync defer on a live connection (the hot shape: no
                # coroutine per call); the async path connects/handles
                # chaos when the fast path declines.
                fut = client.try_send_deferred("PushTask", spec)
                if fut is None:
                    try:
                        fut = await client.send_request("PushTask", spec,
                                                        defer=True)
                    except RpcConnectionError:
                        await self._on_actor_connection_loss(
                            state, spec, pinned, attempt)
                        continue
                # Done-callback, not a coroutine per call: at 10k calls/s
                # a task object per reply is measurable loop overhead.
                # Context rides ON the future as a preallocated tuple
                # and the callback is ONE shared bound method — a
                # 4-default lambda per call allocates a closure each.
                fut._art_actor_ctx = (state, spec, pinned, attempt)
                fut.add_done_callback(self._actor_reply_cb)
                if not state.queue:
                    await self._safe_flush(client)
        finally:
            await self._safe_flush(client)
            state.sender_running = False
            if state.queue:  # raced with a new enqueue
                state.sender_running = True
                _spawn(self._actor_sender(state))

    def _on_actor_reply_done(self, fut: asyncio.Future):
        state, spec, pinned, attempt = fut._art_actor_ctx
        self._on_actor_reply(state, spec, pinned, attempt, fut)

    def _on_actor_reply(self, state, spec, pinned, attempt,
                        fut: asyncio.Future):
        try:
            reply = fut.result()
            self._store_returns(spec, reply["returns"])
            self._unpin(pinned)
        except (RpcConnectionError, asyncio.CancelledError):
            _spawn(self._on_actor_connection_loss(
                state, spec, pinned, attempt))
        except Exception as e:  # noqa: BLE001
            self._store_error(spec, exceptions.ArtError(repr(e)))
            self._unpin(pinned)

    async def _on_actor_connection_loss(self, state, spec, pinned, attempt):
        """The actor's worker went away mid-call.  In-flight tasks fail with
        ActorDiedError unless the task allows retries (ref: actor
        max_task_retries semantics — default 0: death during execution is
        surfaced, not replayed against the restarted instance).  New tasks
        re-resolve the address and reach the restarted actor."""
        self._clients.invalidate(state.address)
        state.address = ""
        info = await self._gcs.call_async(
            "GetActorInfo", {"actor_id": state.actor_id}, timeout=10)
        may_restart = info is not None and info["state"] != ACTOR_DEAD
        if may_restart and attempt < spec.max_retries:
            await asyncio.sleep(min(0.05 * 2 ** attempt, 1.0))
            state.queue.appendleft((spec, pinned, attempt + 1))
            if not state.sender_running:
                state.sender_running = True
                _spawn(self._actor_sender(state))
            return
        if not may_restart:
            state.dead_reason = (info or {}).get(
                "death_reason", "worker connection lost") or "worker died"
            self._release_actor_ctor_pins(state.actor_id)
        self._store_error(spec, exceptions.ActorDiedError(
            state.actor_id,
            (info or {}).get("death_reason", "")
            or "the actor died while this call was executing"))
        self._unpin(pinned)

    # ------------------------------------------------------------ info

    def cluster_resources(self):
        return self._gcs.call("ClusterResources", retries=3)

    def available_resources(self):
        return self._gcs.call("AvailableResources", retries=3)

    def nodes(self):
        infos = self._gcs.call("GetAllNodes", retries=3)
        return [{
            "NodeID": info.node_id.hex(),
            "Alive": info.alive,
            "Address": info.address,
            "Resources": info.total_resources,
            "Labels": info.labels,
            "Draining": getattr(info, "draining", False),
            "DrainReason": getattr(info, "drain_reason", ""),
            "DrainDeadline": getattr(info, "drain_deadline", 0.0),
        } for info in infos.values()]
