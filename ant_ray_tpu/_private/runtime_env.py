"""Runtime environments: per-task/actor env_vars + working_dir
(ref: python/ray/_private/runtime_env/ — the plugin architecture
reduced to its two load-bearing plugins; URI-cached packages live in
GCS KV exactly like the reference caches working_dir zips in the GCS'
internal KV, ref: runtime_env/working_dir.py).

Wire form (what travels in TaskSpec/ActorSpec/lease payloads):
    {"env_vars": {...}, "working_dir_key": "renv:<sha256-16>"}
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import zipfile

MAX_WORKING_DIR_BYTES = 100 * 1024 * 1024


def validate(runtime_env: dict) -> None:
    unknown = set(runtime_env) - {"env_vars", "working_dir"}
    if unknown:
        raise ValueError(
            f"unsupported runtime_env field(s) {sorted(unknown)}; "
            "supported: env_vars, working_dir")
    env_vars = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in env_vars.items()):
        raise ValueError("runtime_env env_vars must be str->str")


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(path):
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > MAX_WORKING_DIR_BYTES:
                    raise ValueError(
                        f"working_dir exceeds "
                        f"{MAX_WORKING_DIR_BYTES >> 20} MiB")
                zf.write(full, rel)
    return buf.getvalue()


def ensure_framework_on_pythonpath(env: dict) -> None:
    """Make child processes able to import a checkout-run framework even
    after a cwd change (shared by worker spawn and job drivers)."""
    import ant_ray_tpu  # noqa: PLC0415

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ant_ray_tpu.__file__)))
    existing = env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(":"):
        env["PYTHONPATH"] = (f"{existing}:{pkg_root}" if existing
                             else pkg_root)


def content_fingerprint(runtime_env: dict) -> str:
    """Cache identity for a runtime env INCLUDING working_dir contents
    (path, size, mtime per file), so edits re-package instead of
    silently reusing a stale zip."""
    parts = [repr(sorted((runtime_env.get("env_vars") or {}).items()))]
    working_dir = runtime_env.get("working_dir")
    if working_dir:
        entries = []
        for root, _dirs, files in os.walk(working_dir):
            for name in files:
                full = os.path.join(root, name)
                try:
                    st = os.stat(full)
                    entries.append((os.path.relpath(full, working_dir),
                                    st.st_size, st.st_mtime_ns))
                except OSError:
                    entries.append((os.path.relpath(full, working_dir),
                                    -1, -1))
        parts.append(repr(sorted(entries)))
        parts.append(working_dir)
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def package(runtime_env: dict | None, kv_put) -> dict | None:
    """Driver side: validate and stage into GCS KV; returns wire form.

    ``kv_put(key, value_bytes)`` uploads content-addressed blobs."""
    if not runtime_env:
        return None
    validate(runtime_env)
    wire: dict = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        wire["env_vars"] = dict(env_vars)
    working_dir = runtime_env.get("working_dir")
    if working_dir:
        if not os.path.isdir(working_dir):
            raise ValueError(f"working_dir {working_dir!r} is not a "
                             "directory")
        blob = _zip_dir(working_dir)
        key = f"renv:{hashlib.sha256(blob).hexdigest()[:16]}"
        kv_put(key, blob)
        wire["working_dir_key"] = key
    return wire or None


def env_key(wire: dict | None) -> str:
    """Stable identity for worker-pool matching: workers are only
    reused for tasks with the same runtime env."""
    if not wire:
        return ""
    return json.dumps(wire, sort_keys=True)


def package_dir(key: str, session_dir: str) -> str:
    return os.path.join(session_dir, "runtime_envs", key.split(":", 1)[1])


def is_extracted(key: str, session_dir: str) -> bool:
    return os.path.exists(os.path.join(package_dir(key, session_dir),
                                       ".art_ready"))


def extract(key: str, blob: bytes, session_dir: str) -> str:
    """Idempotent, race-safe zip extraction; returns the package dir."""
    target = package_dir(key, session_dir)
    if is_extracted(key, session_dir):
        return target
    tmp = target + f".tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    open(os.path.join(tmp, ".art_ready"), "w").close()
    try:
        os.rename(tmp, target)
    except OSError:
        # lost the race to another extractor — use theirs
        import shutil  # noqa: PLC0415

        shutil.rmtree(tmp, ignore_errors=True)
    return target


def resolve(wire: dict | None, session_dir: str) -> tuple[dict, str | None]:
    """(env_overlay, cwd) for a wire env whose packages are already
    extracted (see ``extract``); pure path/dict logic, safe to call on
    an event loop."""
    if not wire:
        return {}, None
    overlay = dict(wire.get("env_vars") or {})
    cwd = None
    key = wire.get("working_dir_key")
    if key:
        if not is_extracted(key, session_dir):
            raise RuntimeError(
                f"runtime_env package {key} not extracted — prefetch it "
                "before spawning")
        cwd = package_dir(key, session_dir)
        # The reference puts working_dir on sys.path of the worker.
        existing = overlay.get("PYTHONPATH", os.environ.get(
            "PYTHONPATH", ""))
        overlay["PYTHONPATH"] = (f"{cwd}:{existing}" if existing
                                 else cwd)
    return overlay, cwd
