"""Runtime environments: per-task/actor env_vars, working_dir,
py_modules and pip venvs (ref: python/ray/_private/runtime_env/ — the
plugin architecture reduced to its load-bearing plugins; URI-cached
packages live in GCS KV exactly like the reference caches working_dir
zips in the GCS' internal KV, ref: runtime_env/working_dir.py,
py_modules.py, pip.py).

Wire form (what travels in TaskSpec/ActorSpec/lease payloads):
    {"env_vars": {...}, "working_dir_key": "renv:<sha256-16>",
     "py_modules_keys": ["renv:<sha>", ...], "pip": ["pkg==1.2", ...]}

``pip`` builds one node-local venv per requirement set (content
addressed, ``--system-site-packages`` so the framework and jax stay
importable) and workers of that env run on the venv's interpreter —
the reference's pip plugin semantics (runtime_env/pip.py) without
per-worker virtualenv duplication.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import zipfile

logger = logging.getLogger(__name__)

MAX_WORKING_DIR_BYTES = 100 * 1024 * 1024


def _package_list(field: str, value) -> list:
    if isinstance(value, dict):
        value = value.get("packages")
    if not (isinstance(value, (list, tuple))
            and all(isinstance(p, str) for p in value)):
        raise ValueError(
            f"runtime_env {field} must be a list of requirement "
            "strings or {'packages': [...]}")
    return list(value)


def validate(runtime_env: dict) -> None:
    unknown = set(runtime_env) - {"env_vars", "working_dir",
                                  "py_modules", "pip", "uv", "conda",
                                  "container"}
    if unknown:
        raise ValueError(
            f"unsupported runtime_env field(s) {sorted(unknown)}; "
            "supported: env_vars, working_dir, py_modules, pip, uv, "
            "conda, container")
    exclusive = [f for f in ("pip", "uv", "conda", "container")
                 if runtime_env.get(f)]
    if len(exclusive) > 1:
        raise ValueError(
            f"runtime_env fields {exclusive} are mutually exclusive — "
            "a worker runs in exactly one python environment")
    if runtime_env.get("uv") is not None:
        _package_list("uv", runtime_env["uv"])
    conda = runtime_env.get("conda")
    if conda is not None:
        if not isinstance(conda, (str, dict)):
            raise ValueError(
                "runtime_env conda must be an existing env name (str) "
                "or an environment.yml dict")
        if isinstance(conda, dict) and not conda.get("name"):
            raise ValueError(
                "runtime_env conda yaml dicts need a 'name' field")
    container = runtime_env.get("container")
    if container is not None and not (
            isinstance(container, dict) and container.get("image")):
        raise ValueError(
            "runtime_env container must be {'image': <image>, ...}")
    env_vars = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in env_vars.items()):
        raise ValueError("runtime_env env_vars must be str->str")
    py_modules = runtime_env.get("py_modules") or []
    if not isinstance(py_modules, (list, tuple)) or not all(
            isinstance(p, (str, os.PathLike)) for p in py_modules):
        raise ValueError(
            "runtime_env py_modules must be a list of path strings "
            "(or PathLike)")
    pip = runtime_env.get("pip")
    if pip is not None:
        if isinstance(pip, dict):
            pip = pip.get("packages")
        if not (isinstance(pip, (list, tuple))
                and all(isinstance(p, str) for p in pip)):
            raise ValueError(
                "runtime_env pip must be a list of requirement strings "
                "or {'packages': [...]}")


def _zip_dir(path: str, prefix: str = "") -> bytes:
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(path):
            for name in files:
                full = os.path.join(root, name)
                rel = os.path.join(prefix, os.path.relpath(full, path))
                total += os.path.getsize(full)
                if total > MAX_WORKING_DIR_BYTES:
                    raise ValueError(
                        f"package {path!r} exceeds "
                        f"{MAX_WORKING_DIR_BYTES >> 20} MiB")
                zf.write(full, rel)
    return buf.getvalue()


def ensure_framework_on_pythonpath(env: dict) -> None:
    """Make child processes able to import a checkout-run framework even
    after a cwd change (shared by worker spawn and job drivers)."""
    import ant_ray_tpu  # noqa: PLC0415

    pkg_root = os.path.dirname(os.path.dirname(
        os.path.abspath(ant_ray_tpu.__file__)))
    existing = env.get("PYTHONPATH", "")
    if pkg_root not in existing.split(":"):
        env["PYTHONPATH"] = (f"{existing}:{pkg_root}" if existing
                             else pkg_root)


def _dir_entries(path: str) -> list:
    entries = []
    for root, _dirs, files in os.walk(path):
        for name in files:
            full = os.path.join(root, name)
            try:
                st = os.stat(full)
                entries.append((os.path.relpath(full, path),
                                st.st_size, st.st_mtime_ns))
            except OSError:
                entries.append((os.path.relpath(full, path), -1, -1))
    return sorted(entries)


def content_fingerprint(runtime_env: dict) -> str:
    """Cache identity covering EVERY field that affects the wire form
    (env_vars, working_dir and py_modules contents — path, size, mtime
    per file — and the pip list), so edits re-package instead of
    silently reusing a stale wire and two different envs can never
    collide on an empty fingerprint."""
    parts = [repr(sorted((runtime_env.get("env_vars") or {}).items()))]
    working_dir = runtime_env.get("working_dir")
    if working_dir:
        parts.append("wd:" + working_dir)
        parts.append(repr(_dir_entries(working_dir)))
    for mod_path in runtime_env.get("py_modules") or ():
        mod_path = os.fspath(mod_path)
        parts.append("mod:" + mod_path)
        if os.path.isdir(mod_path):
            parts.append(repr(_dir_entries(mod_path)))
        else:
            try:
                st = os.stat(mod_path)
                parts.append(repr((st.st_size, st.st_mtime_ns)))
            except OSError:
                parts.append("missing")
    pip = runtime_env.get("pip")
    if pip:
        if isinstance(pip, dict):
            pip = pip.get("packages") or []
        parts.append("pip:" + repr(sorted(pip)))
    uv = runtime_env.get("uv")
    if uv:
        if isinstance(uv, dict):
            uv = uv.get("packages") or []
        parts.append("uv:" + repr(sorted(uv)))
    if runtime_env.get("conda"):
        parts.append("conda:" + json.dumps(runtime_env["conda"],
                                           sort_keys=True))
    if runtime_env.get("container"):
        parts.append("container:" + json.dumps(runtime_env["container"],
                                               sort_keys=True))
    return hashlib.sha256("|".join(parts).encode()).hexdigest()


def _zip_module(path: str) -> bytes:
    """Package one py_module: a directory (kept under its basename, so
    extraction + PYTHONPATH makes ``import <basename>`` work) or a
    single ``.py`` file.  Same size cap as working_dir."""
    if os.path.isdir(path):
        return _zip_dir(path,
                        prefix=os.path.basename(os.path.normpath(path)))
    if os.path.isfile(path) and path.endswith(".py"):
        if os.path.getsize(path) > MAX_WORKING_DIR_BYTES:
            raise ValueError(f"py_module {path!r} exceeds "
                             f"{MAX_WORKING_DIR_BYTES >> 20} MiB")
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.write(path, os.path.basename(path))
        return buf.getvalue()
    raise ValueError(f"py_module {path!r} is neither a package "
                     "directory nor a .py file")


def package(runtime_env: dict | None, kv_put) -> dict | None:
    """Driver side: validate and stage into GCS KV; returns wire form.

    ``kv_put(key, value_bytes)`` uploads content-addressed blobs."""
    if not runtime_env:
        return None
    validate(runtime_env)
    wire: dict = {}
    env_vars = runtime_env.get("env_vars")
    if env_vars:
        wire["env_vars"] = dict(env_vars)
    working_dir = runtime_env.get("working_dir")
    if working_dir:
        if not os.path.isdir(working_dir):
            raise ValueError(f"working_dir {working_dir!r} is not a "
                             "directory")
        blob = _zip_dir(working_dir)
        key = f"renv:{hashlib.sha256(blob).hexdigest()[:16]}"
        kv_put(key, blob)
        wire["working_dir_key"] = key
    keys = []
    for mod_path in runtime_env.get("py_modules") or ():
        blob = _zip_module(os.fspath(mod_path))
        key = f"renv:{hashlib.sha256(blob).hexdigest()[:16]}"
        kv_put(key, blob)
        keys.append(key)
    if keys:
        wire["py_modules_keys"] = keys
    pip = runtime_env.get("pip")
    if pip:
        if isinstance(pip, dict):
            pip = pip.get("packages")
        wire["pip"] = sorted(pip)
    uv = runtime_env.get("uv")
    if uv:
        wire["uv"] = sorted(_package_list("uv", uv))
    if runtime_env.get("conda"):
        wire["conda"] = runtime_env["conda"]
    if runtime_env.get("container"):
        wire["container"] = dict(runtime_env["container"])
    return wire or None


def env_key(wire: dict | None) -> str:
    """Stable identity for worker-pool matching: workers are only
    reused for tasks with the same runtime env."""
    if not wire:
        return ""
    return json.dumps(wire, sort_keys=True)


def package_dir(key: str, session_dir: str) -> str:
    return os.path.join(session_dir, "runtime_envs", key.split(":", 1)[1])


def is_extracted(key: str, session_dir: str) -> bool:
    return os.path.exists(os.path.join(package_dir(key, session_dir),
                                       ".art_ready"))


def extract(key: str, blob: bytes, session_dir: str) -> str:
    """Idempotent, race-safe zip extraction; returns the package dir."""
    target = package_dir(key, session_dir)
    if is_extracted(key, session_dir):
        return target
    tmp = target + f".tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(blob)) as zf:
        zf.extractall(tmp)
    open(os.path.join(tmp, ".art_ready"), "w").close()
    try:
        os.rename(tmp, target)
    except OSError:
        # lost the race to another extractor — use theirs
        import shutil  # noqa: PLC0415

        shutil.rmtree(tmp, ignore_errors=True)
    return target


def resolve(wire: dict | None, session_dir: str) -> tuple[dict, str | None]:
    """(env_overlay, cwd) for a wire env whose packages are already
    extracted (see ``extract``); pure path/dict logic, safe to call on
    an event loop."""
    if not wire:
        return {}, None
    overlay = dict(wire.get("env_vars") or {})
    cwd = None
    paths = []
    key = wire.get("working_dir_key")
    if key:
        if not is_extracted(key, session_dir):
            raise RuntimeError(
                f"runtime_env package {key} not extracted — prefetch it "
                "before spawning")
        cwd = package_dir(key, session_dir)
        # The reference puts working_dir on sys.path of the worker.
        paths.append(cwd)
    for mkey in wire.get("py_modules_keys") or ():
        if not is_extracted(mkey, session_dir):
            raise RuntimeError(
                f"runtime_env package {mkey} not extracted — prefetch "
                "it before spawning")
        paths.append(package_dir(mkey, session_dir))
    if paths:
        existing = overlay.get("PYTHONPATH", os.environ.get(
            "PYTHONPATH", ""))
        joined = ":".join(paths)
        overlay["PYTHONPATH"] = (f"{joined}:{existing}" if existing
                                 else joined)
    venv = None
    if wire.get("pip"):
        venv = venv_dir(wire["pip"], session_dir, "pip")
    elif wire.get("uv"):
        venv = venv_dir(wire["uv"], session_dir, "uv")
    if venv:
        overlay["VIRTUAL_ENV"] = venv
        overlay["PATH"] = (f"{venv}/bin:"
                           + overlay.get("PATH", os.environ.get("PATH", "")))
    return overlay, cwd


# ------------------------------------------------------------------ pip

import threading as _threading

_venv_build_locks: dict = {}
_venv_build_locks_guard = _threading.Lock()


def venv_dir(pip: list, session_dir: str, tool: str = "pip") -> str:
    ident = hashlib.sha256(
        json.dumps([tool, sorted(pip)]).encode()).hexdigest()[:16]
    return os.path.join(session_dir, "venvs", ident)


def conda_env_name(conda) -> str:
    """The node-side env name: user-named envs as given; yaml envs get
    a content-hash suffix so changed dependencies under the same name
    rebuild instead of silently reusing the stale env (the same
    content-addressing venv_dir gives pip/uv)."""
    if isinstance(conda, str):
        return conda
    digest = hashlib.sha256(
        json.dumps(conda, sort_keys=True).encode()).hexdigest()[:8]
    return f"{conda['name']}-art{digest}"


# env name -> resolved interpreter path; populated by ensure_env_ready
# on an executor thread so the spawn path never blocks the event loop
# on a `conda run` subprocess.
_conda_python_cache: dict = {}


def _conda_exe() -> str:
    """The node's conda executable — the single place the
    conda-not-installed error comes from."""
    import shutil  # noqa: PLC0415

    exe = shutil.which("conda")
    if exe is None:
        raise RuntimeError(
            "runtime_env conda requires the conda executable on the "
            "node; it is not installed here (use pip/uv runtime envs, "
            "or install miniconda on every node)")
    return exe


def conda_python(conda) -> str:
    """Interpreter of an EXISTING conda env (ref: runtime_env/conda.py
    — named envs resolve to their prefix; yaml envs are created by
    ensure_env_ready)."""
    import subprocess  # noqa: PLC0415

    name = conda_env_name(conda)
    cached = _conda_python_cache.get(name)
    if cached is not None:
        return cached
    exe = _conda_exe()
    proc = subprocess.run(
        [exe, "run", "-n", name, "python", "-c",
         "import sys; print(sys.executable)"],
        capture_output=True, text=True, timeout=120)
    if proc.returncode != 0:
        raise RuntimeError(
            f"conda env {name!r} is not usable:\n{proc.stderr[-1000:]}")
    path = proc.stdout.strip()
    _conda_python_cache[name] = path
    return path


def venv_python(wire: dict | None, session_dir: str) -> str | None:
    """Interpreter for the env's isolated python, or None when the env
    uses the parent interpreter."""
    wire = wire or {}
    if wire.get("pip"):
        return os.path.join(venv_dir(wire["pip"], session_dir),
                            "bin", "python")
    if wire.get("uv"):
        return os.path.join(venv_dir(wire["uv"], session_dir, "uv"),
                            "bin", "python")
    if wire.get("conda"):
        return conda_python(wire["conda"])
    return None


import contextlib as _contextlib


@_contextlib.contextmanager
def _disk_build_lock(session_dir: str, tag: str):
    """Cross-PROCESS build serialization: the daemon's in-process
    fallback and the node agent can race to build the same env (the
    agent comes up mid-build); an flock on a session-local lockfile
    makes the loser wait and then see the winner's ready marker.
    In-process threads are already serialized by _venv_build_locks."""
    import fcntl  # noqa: PLC0415

    locks_dir = os.path.join(session_dir, ".build_locks")
    os.makedirs(locks_dir, exist_ok=True)
    with open(os.path.join(locks_dir, tag), "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)


def is_ready(wire: dict | None, session_dir: str) -> bool:
    """Cheap LOCAL readiness probe — the daemon's fast path: when
    everything is already materialized, worker spawn skips both the
    agent RPC and the executor hop."""
    wire = wire or {}
    keys = ([wire["working_dir_key"]] if wire.get("working_dir_key")
            else []) + list(wire.get("py_modules_keys") or ())
    if any(not is_extracted(k, session_dir) for k in keys):
        return False
    if wire.get("pip"):
        return os.path.exists(os.path.join(
            venv_dir(wire["pip"], session_dir, "pip"), ".art_ready"))
    if wire.get("uv"):
        return os.path.exists(os.path.join(
            venv_dir(wire["uv"], session_dir, "uv"), ".art_ready"))
    if wire.get("conda"):
        try:
            return conda_env_name(wire["conda"]) in _conda_python_cache
        except Exception:  # noqa: BLE001 — malformed spec: not ready
            return False
    if wire.get("container"):
        return False      # containers are gated node-side every time
    return True


async def materialize(wire: dict | None, session_dir: str,
                      kv_get) -> None:
    """The ONE build sequence (the node agent and the daemon's
    in-process fallback both run exactly this): fetch + extract staged
    packages via ``await kv_get(key)``, then build the interpreter
    layer (pip/uv/conda/container) off the event loop."""
    import asyncio  # noqa: PLC0415

    wire = wire or {}
    keys = ([wire["working_dir_key"]] if wire.get("working_dir_key")
            else []) + list(wire.get("py_modules_keys") or ())
    for key in keys:
        if is_extracted(key, session_dir):
            continue
        blob = await kv_get(key)
        if blob is None:
            raise RuntimeError(
                f"runtime_env package {key} missing from GCS KV")
        extract(key, blob, session_dir)
    if any(wire.get(f) for f in ("pip", "uv", "conda", "container")):
        # Env materialization is slow (subprocess pip/uv/conda) — off
        # the event loop.
        await asyncio.get_running_loop().run_in_executor(
            None, ensure_env_ready, wire, session_dir)


def ensure_venv(pip: list, session_dir: str, tool: str = "pip") -> str:
    """Build (once) the content-addressed venv for a requirement set.

    ``--system-site-packages`` keeps the framework + jax importable from
    the parent environment; pip/uv only layer the requested packages on
    top (ref: runtime_env/pip.py + runtime_env/uv.py build exactly this
    shape of env).  Blocking — call from a thread, not the event loop.
    """
    target = venv_dir(pip, session_dir, tool)
    ready = os.path.join(target, ".art_ready")
    if os.path.exists(ready):
        return target
    # One build per requirement set per process (concurrent leases land
    # on executor threads that share a pid); _build_venv's uuid suffix
    # keeps cross-process builders off each other's tmp dirs.
    with _venv_build_locks_guard:
        lock = _venv_build_locks.setdefault(target, _threading.Lock())
    with lock:
        if os.path.exists(ready):
            return target
        with _disk_build_lock(session_dir, os.path.basename(target)):
            if os.path.exists(ready):   # another PROCESS built it
                return target
            return _build_venv(pip, target, tool)


def ensure_env_ready(wire: dict, session_dir: str) -> None:
    """Materialize the env's interpreter layer (the slow part the
    daemon prefetches off its event loop): pip/uv venv build, conda
    yaml creation, container gating."""
    import shutil  # noqa: PLC0415
    import subprocess  # noqa: PLC0415

    if wire.get("pip"):
        ensure_venv(wire["pip"], session_dir, "pip")
    elif wire.get("uv"):
        ensure_venv(wire["uv"], session_dir, "uv")
    elif wire.get("conda"):
        conda = wire["conda"]
        if isinstance(conda, dict):
            exe = _conda_exe()
            name = conda_env_name(conda)
            with _disk_build_lock(session_dir, f"conda_{name}"):
                # artlint: disable=blocking-under-lock — serializing
                # the conda build across processes IS the disk lock's
                # purpose; this runs on the daemon's env executor
                # thread, never on the event loop.
                probe = subprocess.run(
                    [exe, "env", "list"], capture_output=True, text=True,
                    timeout=120)
                existing = set()
                for line in probe.stdout.splitlines():
                    if line and not line.startswith("#"):
                        first = line.split()[0]
                        existing.add(os.path.basename(first))
                if name not in existing:
                    spec = dict(conda, name=name)
                    spec_path = os.path.join(session_dir,
                                             f"conda_{name}.yml")
                    import yaml as _yaml  # noqa: PLC0415

                    with open(spec_path, "w") as f:
                        _yaml.safe_dump(spec, f)
                    # artlint: disable=blocking-under-lock — same
                    # deliberate build serialization as the probe above.
                    proc = subprocess.run(
                        [exe, "env", "create", "-f", spec_path],
                        capture_output=True, text=True, timeout=1800)
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"conda env create failed:"
                            f"\n{proc.stderr[-2000:]}")
        conda_python(conda)   # resolve + CACHE now (executor thread),
        #                       so the spawn path is pure dict lookup
    elif wire.get("container"):
        if shutil.which("podman") is None and \
                shutil.which("docker") is None:
            raise RuntimeError(
                "runtime_env container requires podman or docker on "
                "the node; neither is installed here (ref: "
                "runtime_env image_uri plugin)")
        raise RuntimeError(
            "container runtime envs are not wired to the worker "
            "launcher yet — run the cluster inside the image instead")


def _build_venv(pip: list, target: str, tool: str = "pip") -> str:
    import shutil as _shutil  # noqa: PLC0415
    import subprocess  # noqa: PLC0415
    import sys  # noqa: PLC0415
    import uuid as _uuid  # noqa: PLC0415

    use_uv = tool == "uv" and _shutil.which("uv") is not None
    if tool == "uv" and not use_uv:
        logger.warning("runtime_env uv requested but the uv binary is "
                       "missing — building with venv+pip instead")
    tmp = target + f".tmp.{os.getpid()}.{_uuid.uuid4().hex[:8]}"
    if use_uv:
        # uv resolves + installs an order of magnitude faster than pip
        # (ref: runtime_env/uv.py — same env shape, faster builder).
        proc = subprocess.run(
            ["uv", "venv", "--system-site-packages",
             "--python", sys.executable, tmp],
            capture_output=True, text=True)
    else:
        proc = subprocess.run(
            [sys.executable, "-m", "venv", "--system-site-packages",
             tmp],
            capture_output=True, text=True)
    if proc.returncode != 0:
        import shutil  # noqa: PLC0415

        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeError(
            f"venv creation failed:\n{proc.stderr[-2000:]}")
    # --system-site-packages chains to the BASE interpreter's
    # site-packages; when this process itself runs in a venv (the
    # common deployment), the parent's packages (jax, cloudpickle, …)
    # live elsewhere — chain them explicitly with a .pth so child
    # workers keep the full parent environment underneath the pip layer.
    import glob  # noqa: PLC0415
    import site  # noqa: PLC0415

    parent_sites = [p for p in site.getsitepackages() if os.path.isdir(p)]
    for sp in glob.glob(os.path.join(tmp, "lib", "python*",
                                     "site-packages")):
        with open(os.path.join(sp, "_art_parent.pth"), "w") as f:
            f.write("\n".join(parent_sites) + "\n")
    if use_uv:
        proc = subprocess.run(
            ["uv", "pip", "install", "--python",
             os.path.join(tmp, "bin", "python"), *pip],
            capture_output=True, text=True)
    else:
        proc = subprocess.run(
            [os.path.join(tmp, "bin", "python"), "-m", "pip",
             "install", "--no-input", *pip],
            capture_output=True, text=True)
    if proc.returncode != 0:
        import shutil  # noqa: PLC0415

        shutil.rmtree(tmp, ignore_errors=True)
        raise RuntimeError(
            f"{tool} install {pip} failed:\n{proc.stderr[-2000:]}")
    open(os.path.join(tmp, ".art_ready"), "w").close()
    try:
        os.rename(tmp, target)
    except OSError:  # lost the build race — use the winner's venv
        import shutil  # noqa: PLC0415

        shutil.rmtree(tmp, ignore_errors=True)
    return target
