"""Global control store (GCS) — the cluster head.

Role of the reference's gcs_server (ref: src/ray/gcs/gcs_server.h:99): owns
the cluster tables (nodes, actors, jobs, named actors, KV, object directory),
performs actor scheduling, health-checks nodes, and answers placement
queries.  All handlers run on the single IO-thread event loop, so table
access needs no locks.  Storage is in-memory round 1 (the store-client
abstraction for Redis persistence comes with HA).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field

from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID
from ant_ray_tpu._private.protocol import ClientPool, IoThread, RpcServer
from ant_ray_tpu._private.specs import (
    ACTOR_ALIVE,
    ACTOR_DEAD,
    ACTOR_PENDING,
    ACTOR_RESTARTING,
    ActorSpec,
    NodeInfo,
)

logger = logging.getLogger(__name__)


@dataclass
class ActorRecord:
    spec: ActorSpec
    state: str = ACTOR_PENDING
    address: str = ""             # worker RPC addr once alive
    node_id: NodeID | None = None
    restarts_used: int = 0
    death_reason: str = ""
    state_event: asyncio.Event = field(default_factory=asyncio.Event)


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._server = RpcServer(host, port)
        self._nodes: dict[NodeID, NodeInfo] = {}
        self._last_heartbeat: dict[NodeID, float] = {}
        self._actors: dict[ActorID, ActorRecord] = {}
        self._named_actors: dict[tuple[str, str], ActorID] = {}
        self._kv: dict[str, bytes] = {}
        self._object_locations: dict[ObjectID, set[NodeID]] = {}
        self._jobs: dict[JobID, dict] = {}
        self._clients = ClientPool()
        self._io = IoThread.get()
        self._health_task = None
        self.address = ""

    # ------------------------------------------------------------- lifecycle

    def start(self) -> str:
        self._server.routes({
            "RegisterNode": self._register_node,
            "Heartbeat": self._heartbeat,
            "GetAllNodes": self._get_all_nodes,
            "KVPut": self._kv_put,
            "KVGet": self._kv_get,
            "KVDel": self._kv_del,
            "KVKeys": self._kv_keys,
            "RegisterJob": self._register_job,
            "CreateActor": self._create_actor,
            "GetActorInfo": self._get_actor_info,
            "WaitActorAlive": self._wait_actor_alive,
            "GetNamedActor": self._get_named_actor,
            "KillActor": self._kill_actor,
            "ActorStateUpdate": self._actor_state_update,
            "WorkerDied": self._worker_died,
            "ObjectLocationAdd": self._object_location_add,
            "ObjectLocationRemove": self._object_location_remove,
            "ObjectLocationsGet": self._object_locations_get,
            "FreeObject": self._free_object,
            "SelectNode": self._select_node,
            "ClusterResources": self._cluster_resources,
            "AvailableResources": self._available_resources,
            "Shutdown": self._shutdown_rpc,
        })
        self.address = self._server.start()
        self._health_task = asyncio.run_coroutine_threadsafe(
            self._health_check_loop(), self._io.loop)
        logger.info("GCS listening on %s", self.address)
        return self.address

    def stop(self):
        if self._health_task is not None:
            self._health_task.cancel()
        self._server.stop()
        self._clients.close_all()

    async def _shutdown_rpc(self, _payload):
        loop = asyncio.get_running_loop()
        loop.call_later(0.05, self.stop)
        return True

    # ------------------------------------------------------------- nodes

    async def _register_node(self, info: NodeInfo):
        self._nodes[info.node_id] = info
        self._last_heartbeat[info.node_id] = time.monotonic()
        logger.info("node %s registered at %s", info.node_id.hex()[:8],
                    info.address)
        return True

    async def _heartbeat(self, payload):
        node_id = payload["node_id"]
        info = self._nodes.get(node_id)
        if info is None:
            return {"unknown_node": True}  # node must re-register
        info.available_resources = payload["available_resources"]
        self._last_heartbeat[node_id] = time.monotonic()
        return {}

    async def _get_all_nodes(self, _payload):
        return dict(self._nodes)

    async def _health_check_loop(self):
        cfg = global_config()
        period = cfg.heartbeat_period_s
        timeout = cfg.heartbeat_period_s * cfg.num_heartbeats_timeout
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node_id, info in list(self._nodes.items()):
                if info.alive and now - self._last_heartbeat[node_id] > timeout:
                    logger.warning("node %s missed heartbeats; marking dead",
                                   node_id.hex()[:8])
                    await self._on_node_death(node_id)

    async def _on_node_death(self, node_id: NodeID):
        info = self._nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        for oid, nodes in list(self._object_locations.items()):
            nodes.discard(node_id)
        for record in list(self._actors.values()):
            if record.node_id == node_id and record.state in (
                    ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING):
                await self._handle_actor_failure(record, "node died")

    # ------------------------------------------------------------- kv

    async def _kv_put(self, payload):
        key, value = payload["key"], payload["value"]
        overwrite = payload.get("overwrite", True)
        if not overwrite and key in self._kv:
            return False
        self._kv[key] = value
        return True

    async def _kv_get(self, payload):
        return self._kv.get(payload["key"])

    async def _kv_del(self, payload):
        return self._kv.pop(payload["key"], None) is not None

    async def _kv_keys(self, payload):
        prefix = payload.get("prefix", "")
        return [k for k in self._kv if k.startswith(prefix)]

    # ------------------------------------------------------------- jobs

    async def _register_job(self, payload):
        self._jobs[payload["job_id"]] = {
            "driver_address": payload.get("driver_address", ""),
            "started_at": time.time(),
        }
        return True

    # ------------------------------------------------------------- actors

    async def _create_actor(self, spec: ActorSpec):
        key = (spec.namespace, spec.name)
        if spec.name:
            existing_id = self._named_actors.get(key)
            if existing_id is not None:
                existing = self._actors.get(existing_id)
                if existing is not None and existing.state != ACTOR_DEAD:
                    return {"error": f"actor name {spec.name!r} already taken",
                            "existing_actor_id": existing_id}
        record = ActorRecord(spec=spec)
        self._actors[spec.actor_id] = record
        if spec.name:
            self._named_actors[key] = spec.actor_id
        asyncio.ensure_future(self._schedule_actor(record))
        return {"ok": True}

    async def _schedule_actor(self, record: ActorRecord):
        spec = record.spec
        placement = spec.placement_resources or spec.resources
        for _attempt in range(60):
            node = self._pick_node(placement)
            if node is not None:
                record.node_id = node.node_id
                client = self._clients.get(node.address)
                try:
                    await client.call_async("StartActorWorker", spec,
                                            timeout=30)
                    return  # worker will report ALIVE via ActorStateUpdate
                except Exception as e:  # noqa: BLE001 — reschedule
                    logger.warning("actor %s placement on %s failed: %s",
                                   spec.actor_id.hex()[:8],
                                   node.node_id.hex()[:8], e)
            await asyncio.sleep(0.5)
        record.state = ACTOR_DEAD
        record.death_reason = "no node with required resources"
        record.state_event.set()

    def _pick_node(self, resources: dict[str, float],
                   by_available: bool = True) -> NodeInfo | None:
        """Least-loaded feasible node (hybrid policy seed).

        by_available=True matches against the (heartbeat-fed, possibly
        stale) availability view; by_available=False against total
        capacity — used to distinguish "busy right now" from "can never
        run" (ref: ClusterResourceScheduler feasibility vs availability).
        """
        best, best_score = None, -1.0
        for info in self._nodes.values():
            if not info.alive:
                continue
            view = (info.available_resources if by_available
                    else info.total_resources)
            if all(view.get(k, 0.0) >= v for k, v in resources.items()):
                total = sum(info.total_resources.values()) or 1.0
                free = sum(info.available_resources.values())
                score = free / total
                if score > best_score:
                    best, best_score = info, score
        return best

    async def _actor_state_update(self, payload):
        actor_id = payload["actor_id"]
        record = self._actors.get(actor_id)
        if record is None:
            return False
        record.state = payload["state"]
        record.address = payload.get("address", record.address)
        if payload.get("node_id") is not None:
            record.node_id = payload["node_id"]
        if record.state == ACTOR_DEAD:
            record.death_reason = payload.get("reason", "")
        record.state_event.set()
        record.state_event = asyncio.Event()
        return True

    async def _get_actor_info(self, payload):
        record = self._actors.get(payload["actor_id"])
        if record is None:
            return None
        return self._actor_info(record)

    def _actor_info(self, record: ActorRecord) -> dict:
        return {
            "actor_id": record.spec.actor_id,
            "state": record.state,
            "address": record.address,
            "node_id": record.node_id,
            "class_name": record.spec.class_name,
            "death_reason": record.death_reason,
            "name": record.spec.name,
        }

    async def _wait_actor_alive(self, payload):
        record = self._actors.get(payload["actor_id"])
        if record is None:
            return None
        deadline = time.monotonic() + payload.get("timeout", 30.0)
        while record.state not in (ACTOR_ALIVE, ACTOR_DEAD):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            event = record.state_event
            try:
                await asyncio.wait_for(event.wait(), remaining)
            except asyncio.TimeoutError:
                break
        return self._actor_info(record)

    async def _get_named_actor(self, payload):
        key = (payload.get("namespace", "default"), payload["name"])
        actor_id = self._named_actors.get(key)
        if actor_id is None:
            return None
        record = self._actors.get(actor_id)
        if record is None or record.state == ACTOR_DEAD:
            return None
        return self._actor_info(record)

    async def _kill_actor(self, payload):
        record = self._actors.get(payload["actor_id"])
        if record is None:
            return False
        record.spec.max_restarts = 0 if payload.get("no_restart", True) else \
            record.spec.max_restarts
        if record.node_id is not None:
            node = self._nodes.get(record.node_id)
            if node is not None and node.alive:
                client = self._clients.get(node.address)
                try:
                    await client.call_async(
                        "KillActorWorker",
                        {"actor_id": record.spec.actor_id}, timeout=10)
                except Exception:  # noqa: BLE001 — worker may be gone already
                    pass
        record.state = ACTOR_DEAD
        record.death_reason = "killed via kill()"
        record.state_event.set()
        return True

    async def _worker_died(self, payload):
        actor_id = payload.get("actor_id")
        if actor_id is not None:
            record = self._actors.get(actor_id)
            if record is not None and record.state != ACTOR_DEAD:
                await self._handle_actor_failure(
                    record, payload.get("reason", "worker died"))
        return True

    async def _handle_actor_failure(self, record: ActorRecord, reason: str):
        if record.restarts_used < record.spec.max_restarts:
            record.restarts_used += 1
            record.state = ACTOR_RESTARTING
            record.address = ""
            record.state_event.set()
            record.state_event = asyncio.Event()
            logger.info("restarting actor %s (%d/%d): %s",
                        record.spec.actor_id.hex()[:8], record.restarts_used,
                        record.spec.max_restarts, reason)
            asyncio.ensure_future(self._schedule_actor(record))
        else:
            record.state = ACTOR_DEAD
            record.death_reason = reason
            record.state_event.set()
            record.state_event = asyncio.Event()

    # ------------------------------------------------------------- objects

    async def _object_location_add(self, payload):
        self._object_locations.setdefault(
            payload["object_id"], set()).add(payload["node_id"])
        return True

    async def _object_location_remove(self, payload):
        locs = self._object_locations.get(payload["object_id"])
        if locs is not None:
            locs.discard(payload["node_id"])
            if not locs:
                del self._object_locations[payload["object_id"]]
        return True

    async def _object_locations_get(self, payload):
        node_ids = self._object_locations.get(payload["object_id"], set())
        return [self._nodes[nid] for nid in node_ids
                if nid in self._nodes and self._nodes[nid].alive]

    async def _free_object(self, payload):
        oid = payload["object_id"]
        node_ids = self._object_locations.pop(oid, set())
        for nid in node_ids:
            node = self._nodes.get(nid)
            if node is None or not node.alive:
                continue
            client = self._clients.get(node.address)
            try:
                await client.oneway_async("DeleteObject", {"object_id": oid})
            except Exception:  # noqa: BLE001
                pass
        return True

    # ------------------------------------------------------------- placement

    async def _select_node(self, payload):
        resources = payload.get("resources", {})
        exclude = payload.get("exclude")

        def _excluding(by_available: bool) -> NodeInfo | None:
            node = self._pick_node(resources, by_available)
            if node is not None and node.node_id == exclude:
                others = [
                    n for n in self._nodes.values()
                    if n.alive and n.node_id != exclude and all(
                        (n.available_resources if by_available
                         else n.total_resources).get(k, 0) >= v
                        for k, v in resources.items())
                ]
                node = others[0] if others else None
            return node

        # Prefer a node that can run now; fall back to one that is merely
        # busy (the lease queues there) before declaring infeasibility.
        return _excluding(True) or _excluding(False)

    async def _cluster_resources(self, _payload):
        totals: dict[str, float] = {}
        for info in self._nodes.values():
            if info.alive:
                for k, v in info.total_resources.items():
                    totals[k] = totals.get(k, 0.0) + v
        return totals

    async def _available_resources(self, _payload):
        totals: dict[str, float] = {}
        for info in self._nodes.values():
            if info.alive:
                for k, v in info.available_resources.items():
                    totals[k] = totals.get(k, 0.0) + v
        return totals


def main():  # pragma: no cover — exercised via subprocess in tests
    import argparse
    import signal
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--monitor-pid", type=int, default=0,
                        help="exit when this process disappears")
    args = parser.parse_args()

    logging.basicConfig(
        level=global_config().log_level,
        format="[gcs %(levelname)s %(asctime)s] %(message)s")
    server = GcsServer(port=args.port)
    server.start()
    print(f"GCS_READY {server.address}", flush=True)

    stop = False

    def _term(*_a):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop:
        time.sleep(0.2)
        if args.monitor_pid and not os.path.exists(
                f"/proc/{args.monitor_pid}"):
            logger.warning("monitored pid %d gone; exiting", args.monitor_pid)
            break
    server.stop()
    sys.exit(0)


if __name__ == "__main__":
    main()
