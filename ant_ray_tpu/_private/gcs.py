"""Global control store (GCS) — the cluster head.

Role of the reference's gcs_server (ref: src/ray/gcs/gcs_server.h:99): owns
the cluster tables (nodes, actors, jobs, named actors, KV, object directory),
performs actor scheduling, health-checks nodes, and answers placement
queries.  All handlers run on the single IO-thread event loop, so table
access needs no locks.  Storage is in-memory round 1 (the store-client
abstraction for Redis persistence comes with HA).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from dataclasses import dataclass, field

from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID
from ant_ray_tpu._private.protocol import (
    ClientPool,
    IoThread,
    RpcServer,
    _spawn,
)
from ant_ray_tpu._private.specs import (
    ACTOR_ALIVE,
    ACTOR_DEAD,
    ACTOR_PENDING,
    ACTOR_RESTARTING,
    ActorSpec,
    NodeInfo,
)

logger = logging.getLogger(__name__)


@dataclass
class ActorRecord:
    spec: ActorSpec
    state: str = ACTOR_PENDING
    address: str = ""             # worker RPC addr once alive
    node_id: NodeID | None = None
    restarts_used: int = 0
    death_reason: str = ""
    state_event: asyncio.Event = field(default_factory=asyncio.Event)


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store_path: str | None = None,
                 export_dir: str | None = None,
                 ha_replica_id: str | None = None):
        from ant_ray_tpu._private.store_client import (  # noqa: PLC0415
            store_client_for,
        )

        # Export-event pipeline (ref: RayEventRecorder + export_*.proto
        # — durable JSONL lifecycle events for external pipelines);
        # active only when the session provides an export dir.
        self._exporter = None
        if export_dir:
            from ant_ray_tpu._private.export_events import (  # noqa: PLC0415
                ExportEventRecorder,
            )

            self._exporter = ExportEventRecorder(export_dir)

        # Write-through persistence (ref: gcs store clients,
        # src/ray/gcs/store_client/redis_store_client.h): with a store
        # spec, every table mutation lands in the store and a restarted
        # head (same port + store) resumes the cluster — actors stay
        # callable, PGs stay reserved, nodes resync via heartbeats.
        # ``art-store://host:port`` targets the RPC'd store service
        # (store_server.py), which lives OFF this machine so a standby
        # head anywhere can restore the tables (shared-store HA).
        self._store = store_client_for(store_path)
        self._durable = store_path is not None
        # Replicated control plane (gcs_ha.HaCoordinator): with a
        # replica id AND a shared store, this process is one member of
        # a leader + warm-standby set — mutations are fenced on the
        # lease, standbys tail the store and serve follower reads.
        self._ha = None
        if ha_replica_id is not None:
            if not store_path:
                raise ValueError(
                    "GCS HA requires a shared store (--store)")
            from ant_ray_tpu._private.gcs_ha import (  # noqa: PLC0415
                HaCoordinator,
            )

            self._ha = HaCoordinator(self, ha_replica_id, store_path)
        # Tables whose persisted copy lags in-memory truth by at most
        # one flush period (high-churn; see _location_flush_loop).
        self._dirty_nodes: set[NodeID] = set()
        self._metrics_dirty = False
        # Store-generation counter: bumped once per flush period in
        # which ANY table write happened, advertised in the leader ad —
        # followers skip the full table re-read when it hasn't moved,
        # so idle-cluster sync cost is O(1), not O(state).
        self._store_gen = 0
        self._store_gen_dirty = False
        self._server = RpcServer(host, port)
        self._nodes: dict[NodeID, NodeInfo] = {}
        self._last_heartbeat: dict[NodeID, float] = {}
        # Versioned resource-view sync: highest view version applied per
        # node (ref: ray_syncer NodeState version tracking).  Absent
        # after a restart -> the node is commanded to resync.
        self._node_view_versions: dict[NodeID, int] = {}
        self._spread_rr = 0       # SPREAD strategy round-robin cursor
        self._actors: dict[ActorID, ActorRecord] = {}
        self._named_actors: dict[tuple[str, str], ActorID] = {}
        self._kv: dict[str, bytes] = {}
        self._object_locations: dict[ObjectID, set[NodeID]] = {}
        self._jobs: dict[JobID, dict] = {}
        self._placement_groups: dict = {}  # pg_id -> record dict
        self._metrics: dict[tuple, dict] = {}  # (name, tags) -> series
        # vc_id -> {"node_ids": set[NodeID], "divisible": bool, ...}
        # (ant-fork capability: GcsVirtualClusterManager,
        #  src/ray/gcs/gcs_virtual_cluster_manager.h:30)
        self._virtual_clusters: dict[str, dict] = {}
        self._job_vc: dict[JobID, str] = {}
        from collections import deque  # noqa: PLC0415

        # bounded ring of task lifecycle events (ref: the GCS task-event
        # aggregator fed by core-worker TaskEventBuffers)
        self._task_events: deque = deque(maxlen=50000)
        # bounded per-(task, attempt) state table folded at ingestion —
        # ListTasks/GetTask/SummarizeTasks answer from THIS, never by
        # replaying the raw ring (ref: GcsTaskManager's task table,
        # gcs_task_manager.h:97)
        from ant_ray_tpu._private.task_state import (  # noqa: PLC0415
            TaskStateTable,
        )

        self._task_state = TaskStateTable()
        # client-side flush drops reported by TaskEventBuffers (each
        # TaskEventsAdd carries the producer's delta) — surfaced in the
        # state-API stats so a lossy view is never silent
        self._task_events_dropped = 0
        # object directory sidecar: owner address (+ optional creation
        # callsite) per object, reported by the sealing daemon — the
        # memory-attribution join reads it back via ListObjects
        self._object_meta: dict[ObjectID, dict] = {}
        # bounded ring of flow-insight events (ant-fork, util/insight)
        self._insight_events: deque = deque(maxlen=10000)
        # bounded ring of per-step profiler records (observability/
        # step_profiler.py — merged into the timeline as device rows)
        self._step_events: deque = deque(maxlen=20000)
        # bounded ring of request-trace spans (observability/
        # tracing_plane.py — batch-published per-process flight
        # recorders; /api/trace/{id} and the timeline read it back)
        self._span_events: deque = deque(maxlen=50000)
        # bounded ring of folded-stack CPU-profile deltas (observability/
        # cpu_profiler.py — one record per process per publish period;
        # the CLI `profile` capture and /api/cpuprofile read it back)
        self._cpu_profile: deque = deque(maxlen=4000)
        self._dirty_locations: set[ObjectID] = set()
        # ---- pubsub (ref: src/ray/pubsub/publisher.h — long-poll
        # channels; here one global sequence + per-event channel tag so a
        # subscriber resumes from a single cursor)
        self._pub_events: deque = deque(maxlen=4096)
        self._pub_seq = 0
        self._pub_cond: asyncio.Condition | None = None  # lazy (io loop)
        self._pub_notify_pending = False
        # Unfulfilled scheduling demands (autoscaler input): canonical
        # (resources, selector) -> {count, first_seen, last_seen}.
        self._demands: dict[str, dict] = {}
        # ---- scale observatory counters (benchmarks/scale_harness.py
        # reads these back via GetScaleStats to decompose control-plane
        # cost per node by subsystem) ----
        self._init_sched_observatory()
        # Heartbeat ingest: beats handled and versioned views applied.
        self._hb_stats = {"beats": 0, "views_applied": 0,
                          "unknown_node": 0}
        # Long-pollers currently parked in _sub_poll (subscriber gauge).
        self._sub_pollers = 0
        # io-loop duty cursor: (io_samples, io_idle_samples) at the
        # last _io_loop_duty() reading, so each reading is a window
        # fraction instead of a since-boot average.
        self._io_duty_cursor = (0, 0)
        # None until the first heartbeat — 0.0 would read as "recently
        # seen" on a host whose monotonic clock is near boot.
        self._autoscaler_seen: float | None = None
        self._clients = ClientPool()
        self._io = IoThread.get()
        self._health_task = None
        self.address = ""

    # ------------------------------------------------------------- lifecycle

    def start(self) -> str:
        handlers = {
            "RegisterNode": self._register_node,
            "Heartbeat": self._heartbeat,
            "GetAllNodes": self._get_all_nodes,
            "ListNodes": self._list_nodes,
            "GetScaleStats": self._get_scale_stats,
            "DrainNode": self._drain_node,
            "KVPut": self._kv_put,
            "KVGet": self._kv_get,
            "KVDel": self._kv_del,
            "KVTake": self._kv_take,
            "KVKeys": self._kv_keys,
            "RegisterJob": self._register_job,
            "CreateActor": self._create_actor,
            "GetActorInfo": self._get_actor_info,
            "WaitActorAlive": self._wait_actor_alive,
            "GetNamedActor": self._get_named_actor,
            "KillActor": self._kill_actor,
            "ActorStateUpdate": self._actor_state_update,
            "WorkerDied": self._worker_died,
            "ObjectLocationAdd": self._object_location_add,
            "ObjectLocationRemove": self._object_location_remove,
            "ObjectLocationsGet": self._object_locations_get,
            "FreeObject": self._free_object,
            "SelectNode": self._select_node,
            "ResourceDemands": self._resource_demands,
            "AutoscalerHeartbeat": self._autoscaler_heartbeat,
            "AutoscalingEnabled": self._autoscaling_enabled,
            "ClusterResources": self._cluster_resources,
            "AvailableResources": self._available_resources,
            "CreatePlacementGroup": self._create_placement_group,
            "GetPlacementGroup": self._get_placement_group,
            "RemovePlacementGroup": self._remove_placement_group,
            "ListPlacementGroups": self._list_placement_groups,
            "ListActors": self._list_actors,
            "ListObjects": self._list_objects,
            "MetricRecord": self._metric_record,
            "MetricsGet": self._metrics_get,
            "CreateVirtualCluster": self._create_virtual_cluster,
            "RemoveVirtualCluster": self._remove_virtual_cluster,
            "UpdateVirtualCluster": self._update_virtual_cluster,
            "ListVirtualClusters": self._list_virtual_clusters,
            "SetJobVirtualCluster": self._set_job_virtual_cluster,
            "GetJobVirtualCluster": self._get_job_virtual_cluster,
            "InsightRecord": self._insight_record,
            "InsightGet": self._insight_get,
            "TaskEventsAdd": self._task_events_add,
            "TaskEventsGet": self._task_events_get,
            "ListTasks": self._list_tasks,
            "GetTask": self._get_task,
            "SummarizeTasks": self._summarize_tasks,
            "ListJobs": self._list_jobs,
            "StepEventsAdd": self._step_events_add,
            "StepEventsGet": self._step_events_get,
            "SpanEventsAdd": self._span_events_add,
            "SpanEventsGet": self._span_events_get,
            "CpuProfileAdd": self._cpu_profile_add,
            "CpuProfileGet": self._cpu_profile_get,
            "MetricsExpire": self._metrics_expire,
            "GetHaView": self._get_ha_view,
            "SubPoll": self._sub_poll,
            "PublishLogs": self._publish_logs,
            "ExportEventsGet": self._export_events_get,
            "Shutdown": self._shutdown_rpc,
        }
        if self._ha is not None:
            # Fence leader-only methods; reads and ring writes stay
            # servable on any replica (split defined in wire_schema).
            handlers = self._ha.guard_routes(handlers)
        self._server.routes(handlers)
        if self._durable and self._ha is None:
            # Plain restart-FT: re-hydrate before serving.  HA replicas
            # re-hydrate continuously (standby sync loop) and fully at
            # promotion instead.
            self._load_tables()
        self.address = self._server.start()
        self._health_task = asyncio.run_coroutine_threadsafe(
            self._health_check_loop(), self._io.loop)
        if self._durable:
            self._flush_task = asyncio.run_coroutine_threadsafe(
                self._location_flush_loop(), self._io.loop)
        if self._ha is not None:
            self._ha.start()
        # Continuous CPU profiling: the GCS ingests its own records —
        # the publisher appends straight into the local ring (each HA
        # replica keeps its own shard; CpuProfileGet merges at query
        # time) and metric rollups run through the local handler on the
        # io loop.  Instance profiler, not the module singleton: HA
        # tests run several replicas in one process.
        from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

        self._cpu_profiler = None
        if global_config().cpu_profile_hz > 0:
            def _publish_profile(record, server=self):
                server._cpu_profile.append(record)

            def _publish_metric(payload, server=self):
                asyncio.run_coroutine_threadsafe(
                    server._metric_record(payload), server._io.loop)

            self._cpu_profiler = cpu_profiler.CpuProfiler(
                "gcs", publish_fn=_publish_profile,
                metric_fn=_publish_metric,
                node_id=(f"gcs-{self._ha.replica_id}"
                         if self._ha is not None else "gcs")).start()
        logger.info("GCS listening on %s%s", self.address,
                    f" (HA replica {self._ha.replica_id})"
                    if self._ha is not None else "")
        return self.address

    def _leading(self) -> bool:
        """True when this process owns the cluster (non-HA, or the HA
        leader): the health-check and flush loops no-op on standbys."""
        return self._ha is None or self._ha.is_leader_active()

    async def _get_ha_view(self, _payload):
        if self._ha is None:
            return {"ha": False, "role": "leader",
                    "replica_id": None, "address": self.address,
                    "leader": self.address, "term": 0,
                    "last_failover_ts": None,
                    "replication_lag_s": None, "replicas": []}
        return self._ha.view()

    # ---------------------------------------------------- persistence

    def _persist(self, table: str, key: str, value) -> None:
        if self._durable:
            import pickle  # noqa: PLC0415

            self._store.put(table, key, pickle.dumps(value))
            self._store_gen_dirty = True

    def _persist_del(self, table: str, key: str) -> None:
        if self._durable:
            self._store.delete(table, key)
            self._store_gen_dirty = True

    def _save_actor(self, record: ActorRecord) -> None:
        self._persist("actors", record.spec.actor_id.hex(), {
            "spec": record.spec, "state": record.state,
            "address": record.address, "node_id": record.node_id,
            "restarts_used": record.restarts_used,
            "death_reason": record.death_reason,
        })

    def _save_pg(self, record: dict) -> None:
        self._persist("pgs", record["pg_id"].hex(), record)

    def _save_locations(self, oid) -> None:
        # Object-location churn is the hottest GCS path — a synchronous
        # sqlite commit per event would serialize the whole object plane
        # behind the disk.  Mark dirty; a periodic flusher batches the
        # writes (restart loses at most one flush period of location
        # updates, which heartbeat resync / lineage absorbs).
        if self._durable:
            self._dirty_locations.add(oid)

    async def _location_flush_loop(self):
        while True:
            await asyncio.sleep(0.5)
            if not self._leading():
                continue        # standbys tail the store, never write it
            self._flush_locations()
            self._flush_nodes()
            self._flush_metrics()
            if self._store_gen_dirty:
                self._store_gen_dirty = False
                self._store_gen += 1
            if self._ha is not None:
                # Leader heartbeat into the store: redirect target +
                # the wall-clock stamp followers measure lag against +
                # the store generation they sync against.
                self._ha.write_leader_ad()

    def _flush_locations(self) -> None:
        if not self._durable or not self._dirty_locations:
            return
        dirty, self._dirty_locations = self._dirty_locations, set()
        for oid in dirty:
            nodes = self._object_locations.get(oid)
            if nodes:
                self._persist("locations", oid.hex(), (oid, nodes))
            else:
                self._persist_del("locations", oid.hex())

    def _save_node(self, info: NodeInfo) -> None:
        """Immediate node-table persistence for the low-churn
        transitions (register / death / drain); the high-churn
        availability view rides the dirty set + flush loop instead."""
        self._persist("nodes", info.node_id.hex(), info)

    def _flush_nodes(self) -> None:
        if not self._durable or not self._dirty_nodes:
            return
        dirty, self._dirty_nodes = self._dirty_nodes, set()
        for node_id in dirty:
            info = self._nodes.get(node_id)
            if info is not None:
                self._persist("nodes", node_id.hex(), info)

    def _flush_metrics(self) -> None:
        """One pickled blob per flush period when anything changed:
        followers serve metrics scrapes from it, and a restarted head
        resumes its counters instead of zeroing every series."""
        if not self._durable or not self._metrics_dirty:
            return
        self._metrics_dirty = False
        self._persist("misc", "metrics", self._metrics)

    def _save_vcs(self) -> None:
        self._persist("misc", "virtual_clusters", self._virtual_clusters)
        self._persist("misc", "job_vc", self._job_vc)

    def _snapshot_tables_from_store(self) -> dict:
        """Read every persisted table into fresh containers (no side
        effects, callable off the io loop): the follower sync loop and
        the (re)start/promotion loaders share this one reader."""
        import pickle  # noqa: PLC0415

        store = self._store
        snap: dict = {}
        snap["kv"] = {key: pickle.loads(blob)
                      for key, blob in store.load_table("kv").items()}
        jobs = {}
        for _key, blob in store.load_table("jobs").items():
            job_id, info = pickle.loads(blob)
            jobs[job_id] = info
        snap["jobs"] = jobs
        actors: dict = {}
        named: dict = {}
        for _key, blob in store.load_table("actors").items():
            row = pickle.loads(blob)
            record = ActorRecord(
                spec=row["spec"], state=row["state"],
                address=row["address"], node_id=row["node_id"],
                restarts_used=row["restarts_used"],
                death_reason=row["death_reason"])
            actors[record.spec.actor_id] = record
            if record.spec.name and record.state != ACTOR_DEAD:
                named[(record.spec.namespace, record.spec.name)] = \
                    record.spec.actor_id
        snap["actors"] = actors
        snap["named_actors"] = named
        pgs = {}
        for _key, blob in store.load_table("pgs").items():
            record = pickle.loads(blob)
            pgs[record["pg_id"]] = record
        snap["pgs"] = pgs
        locations = {}
        for _key, blob in store.load_table("locations").items():
            oid, nodes = pickle.loads(blob)
            locations[oid] = nodes
        snap["locations"] = locations
        blob = store.get("misc", "virtual_clusters")
        snap["vcs"] = pickle.loads(blob) if blob else {}
        blob = store.get("misc", "job_vc")
        snap["job_vc"] = pickle.loads(blob) if blob else {}
        nodes = {}
        for _key, blob in store.load_table("nodes").items():
            info = pickle.loads(blob)
            nodes[info.node_id] = info
        snap["nodes"] = nodes
        blob = store.get("misc", "metrics")
        snap["metrics"] = pickle.loads(blob) if blob else {}
        return snap

    def _apply_table_snapshot(self, snap: dict) -> None:
        """Swap the snapshot in (io-loop only): whole-container
        assignment, so a concurrently-dispatched read handler sees
        either the previous generation or this one, never a mix."""
        self._kv = snap["kv"]
        self._jobs = snap["jobs"]
        self._actors = snap["actors"]
        self._named_actors = snap["named_actors"]
        self._placement_groups = snap["pgs"]
        self._object_locations = snap["locations"]
        self._virtual_clusters = snap["vcs"]
        self._job_vc = snap["job_vc"]
        self._nodes = snap["nodes"]
        self._metrics = snap["metrics"]

    def _load_tables(self) -> None:
        """Full re-hydrate + activation (restart FT): load every table,
        then activate.  HA promotion snapshots OFF the io loop first
        (a remote store's reads block on that very loop) and calls
        :meth:`_activate_tables` directly."""
        self._activate_tables(self._snapshot_tables_from_store())

    def _activate_tables(self, snap: dict) -> None:
        """Adopt a snapshot and kick the schedulers/reconcilers that a
        passive follower sync must never run."""
        self._apply_table_snapshot(snap)
        # Restored nodes get one full heartbeat-timeout of grace before
        # the health check may declare them dead; their view versions
        # are gone, so the next beat is answered with a resync command.
        now = time.monotonic()
        for node_id in self._nodes:
            self._last_heartbeat[node_id] = now
        self._node_view_versions = {}
        for record in self._actors.values():
            # Actors that were mid-scheduling when the head died get
            # re-kicked once the loop runs (nodes resync via heartbeat).
            if record.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                asyncio.run_coroutine_threadsafe(
                    self._reschedule_after_resync(record), self._io.loop)
        for record in self._placement_groups.values():
            if record["state"] == "PENDING":
                asyncio.run_coroutine_threadsafe(
                    self._schedule_placement_group(record), self._io.loop)
        # Liveness reconciliation: an actor restored as ALIVE may sit on
        # a node that never comes back (its daemon died during the head's
        # downtime, so no WorkerDied report will ever arrive).  After a
        # registration grace period, fail those actors through the normal
        # restart machinery.
        if any(r.state in (ACTOR_ALIVE, ACTOR_RESTARTING)
               for r in self._actors.values()):
            asyncio.run_coroutine_threadsafe(
                self._reconcile_actors_after_restart(), self._io.loop)
        logger.info(
            "restored GCS state: %d actors, %d pgs, %d kv keys, %d jobs"
            ", %d nodes",
            len(self._actors), len(self._placement_groups),
            len(self._kv), len(self._jobs), len(self._nodes))

    async def _reschedule_after_resync(self, record: ActorRecord):
        # Give nodes one heartbeat round to re-register before placing.
        await asyncio.sleep(global_config().heartbeat_period_s * 2)
        await self._schedule_actor(record)

    async def _reconcile_actors_after_restart(self):
        cfg = global_config()
        await asyncio.sleep(
            cfg.heartbeat_period_s * cfg.num_heartbeats_timeout)
        for record in list(self._actors.values()):
            if record.state not in (ACTOR_ALIVE, ACTOR_RESTARTING):
                continue
            node = (self._nodes.get(record.node_id)
                    if record.node_id is not None else None)
            if node is None or not node.alive:
                await self._handle_actor_failure(
                    record, "node lost while the head was down")

    def stop(self, graceful: bool = True):
        """``graceful=False`` (the subprocess SIGTERM path) skips waits
        that need io-loop turns: the loop may be busy reacting to the
        same cluster teardown (node deaths), and the dying process's
        sockets close with it anyway."""
        if self._health_task is not None:
            self._health_task.cancel()
        profiler = getattr(self, "_cpu_profiler", None)
        if profiler is not None:
            self._cpu_profiler = None
            profiler.stop(final_publish=False)
        if self._ha is not None:
            # Releases a held lease so a standby takes over immediately
            # (graceful failover) instead of waiting out the TTL.
            self._ha.stop()
        flush_task = getattr(self, "_flush_task", None)
        if flush_task is not None:
            flush_task.cancel()
            self._flush_locations()  # final batch before shutdown
            self._flush_nodes()
            self._flush_metrics()
        # Drain the store's async write queue: acknowledged mutations
        # must reach the (possibly remote) store before the head exits.
        self._store.close()
        if self._exporter is not None:
            # Terminal lifecycle events (node DEAD, worker DIED) queue
            # milliseconds before shutdown; os._exit in main would drop
            # them from the JSONL files the pipeline promises.
            self._exporter.flush(timeout=2.0)
        if graceful:
            self._server.stop()
            self._clients.close_all()

    async def _shutdown_rpc(self, _payload):
        loop = asyncio.get_running_loop()
        loop.call_later(0.05, self.stop)
        return True

    # ------------------------------------------------------------- pubsub

    def _publish(self, channel: str, data: dict) -> None:
        """Append an event and wake long-pollers (ref: Publisher,
        src/ray/pubsub/publisher.h — the mechanism that lets a thousand
        workers watch actor/node state without hammering the head).
        Wakeups coalesce: a burst of publishes (mass node failure)
        schedules ONE notify, not one per event."""
        self._pub_seq += 1
        self._pub_events.append((self._pub_seq, channel, data))
        if self._exporter is not None and channel != "worker_logs":
            # Mirror control-plane pubsub into the export pipeline:
            # node alive/dead and actor state transitions ARE the
            # lifecycle events external consumers want.
            if channel == "node":
                self._exporter.record(
                    "EXPORT_NODE",
                    "ALIVE" if data.get("alive") else "DEAD",
                    data.get("node_id"), data)
            elif channel == "actor_state":
                self._exporter.record("EXPORT_ACTOR",
                                      str(data.get("state", "")).upper(),
                                      data.get("actor_id"), data)
        if self._pub_cond is not None and not self._pub_notify_pending:
            self._pub_notify_pending = True

            async def _notify():
                self._pub_notify_pending = False
                async with self._pub_cond:
                    self._pub_cond.notify_all()

            _spawn(_notify())

    async def _export_events_get(self, payload):
        """Read back export-pipeline events (dashboard /api and tests;
        external pipelines normally tail the JSONL files directly).
        File parsing runs off the event loop — a full export dir must
        not stall heartbeats and lease RPCs."""
        if self._exporter is None:
            return {"enabled": False, "events": []}
        events = await asyncio.to_thread(
            self._exporter.read, payload.get("source_type"),
            int(payload.get("limit", 1000)))
        return {"enabled": True, "events": events}

    async def _publish_logs(self, payload):
        """Fan worker stdout/stderr lines out to subscribed drivers
        (ref: log_monitor.py → GCS pubsub — the mechanism behind
        `print()` in a task appearing on the driver's console)."""
        self._publish("worker_logs", payload)
        return True

    async def _sub_poll(self, payload):
        """Long-poll subscription: blocks until events newer than the
        caller's cursor exist on its channels (or ~25s passes), then
        returns them with the new cursor."""
        if self._pub_cond is None:
            self._pub_cond = asyncio.Condition()
        channels = set(payload.get("channels") or ())
        cursor = int(payload.get("cursor", 0))
        if cursor < 0:  # "start from now" — skip buffered history
            cursor = self._pub_events[-1][0] if self._pub_events else 0
        elif cursor > self._pub_seq:
            # A cursor ahead of our sequence belongs to a previous
            # leader incarnation (the client's router absorbed the
            # failover, so its error-path resubscribe never ran).
            # Adopt "now" — resuming with the foreign cursor would
            # silence the subscription forever.
            cursor = self._pub_seq
        timeout = min(float(payload.get("timeout", 25.0)), 25.0)
        deadline = time.monotonic() + timeout
        self._sub_pollers += 1
        try:
            while True:
                events = [(seq, ch, data)
                          for seq, ch, data in self._pub_events
                          if seq > cursor
                          and (not channels or ch in channels)]
                latest = (self._pub_events[-1][0]
                          if self._pub_events else cursor)
                if events:
                    return {"cursor": max(cursor, latest),
                            "events": events}
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"cursor": max(cursor, latest), "events": []}
                async with self._pub_cond:
                    try:
                        await asyncio.wait_for(self._pub_cond.wait(),
                                               remaining)
                    except asyncio.TimeoutError:
                        pass
        finally:
            self._sub_pollers -= 1

    # ------------------------------------------------------------- nodes

    async def _register_node(self, info: NodeInfo):
        self._nodes[info.node_id] = info
        self._last_heartbeat[info.node_id] = time.monotonic()
        self._save_node(info)
        # (Re-)registration carries a fresh full view and restarts the
        # node's version counter — drop any stale high-water mark so the
        # node's next deltas aren't rejected as old.
        self._node_view_versions.pop(info.node_id, None)
        self._publish("node", {"node_id": info.node_id, "alive": True,
                               "address": info.address})
        logger.info("node %s registered at %s", info.node_id.hex()[:8],
                    info.address)
        return True

    async def _heartbeat(self, payload):
        """Liveness + versioned resource-view sync (ref:
        src/ray/ray_syncer/ray_syncer.h:90).  A beat without a ``view``
        is pure liveness; one WITH a view applies it if its version is
        newer than what we hold and acks the version, so the node stops
        resending.  After a GCS restart our version table is empty —
        the ``resync`` command tells the node to send a full view."""
        node_id = payload["node_id"]
        self._hb_stats["beats"] += 1
        info = self._nodes.get(node_id)
        if info is None:
            self._hb_stats["unknown_node"] += 1
            return {"unknown_node": True}  # node must re-register
        self._last_heartbeat[node_id] = time.monotonic()
        reply: dict = {}
        view = payload.get("view")
        if view is not None:
            version = view.get("version", 0)
            if version > self._node_view_versions.get(node_id, -1):
                self._hb_stats["views_applied"] += 1
                info.available_resources = view["available_resources"]
                info.disk_full = view.get("disk_full", False)
                # Drain state is STICKY here: the daemon's view can set
                # it (preemption watcher), but never clears it — a node
                # drained via the DrainNode RPC stays drained even if
                # the daemon itself didn't observe the notice.
                if view.get("draining"):
                    self._apply_drain(info, view.get("drain_reason", ""),
                                      view.get("drain_deadline", 0.0))
                self._node_view_versions[node_id] = version
                self._dirty_nodes.add(node_id)
            reply["synced"] = self._node_view_versions[node_id]
        elif node_id not in self._node_view_versions:
            reply["commands"] = ["resync"]
        if "available_resources" in payload:   # legacy full-view beat
            info.available_resources = payload["available_resources"]
            info.disk_full = payload.get("disk_full", False)
        return reply

    async def _get_all_nodes(self, _payload):
        return dict(self._nodes)

    @staticmethod
    def _node_state(info: NodeInfo) -> str:
        if not info.alive:
            return "DEAD"
        if getattr(info, "draining", False):
            return "DRAINING"
        return "ALIVE"

    async def _list_nodes(self, payload):
        """Paginated node listing — the ListTasks cursor idiom applied
        to the node table (the unpaged GetAllNodes reply falls over at
        hundreds of nodes).  Pages walk node-id order; the token is the
        last returned node's hex id, so a node dying (or registering)
        between pages can neither shift nor duplicate the cursor.
        ``state`` filters ALIVE / DEAD / DRAINING server-side."""
        payload = payload or {}
        limit = max(1, int(payload.get("limit", 1000)))
        state = payload.get("state")
        if state is not None:
            state = str(state).upper()
        token = payload.get("token")
        records = []
        next_token = None
        total = matched = 0
        for node_id in sorted(self._nodes, key=lambda n: n.hex()):
            total += 1
            info = self._nodes[node_id]
            node_state = self._node_state(info)
            if state is not None and node_state != state:
                continue
            matched += 1
            if token is not None and node_id.hex() <= token:
                continue
            if len(records) >= limit:
                next_token = records[-1]["node_id"]
                break
            records.append({
                "node_id": node_id.hex(),
                "address": info.address,
                "state": node_state,
                "alive": info.alive,
                "draining": bool(getattr(info, "draining", False)),
                "drain_reason": getattr(info, "drain_reason", ""),
                "disk_full": bool(getattr(info, "disk_full", False)),
                "labels": dict(info.labels or {}),
                "total_resources": dict(info.total_resources),
                "available_resources": dict(info.available_resources),
            })
        return {"nodes": records, "next_token": next_token,
                "total": total, "matched": matched}

    # ------------------------------------------- scale observatory
    # (benchmarks/scale_harness.py + /api/scale + `scale-report`: the
    # per-subsystem cost decomposition that turns "cost per node" from
    # one opaque number into attributable curves)

    def _io_loop_duty(self) -> float | None:
        """Busy fraction of the io thread over the window since the
        last call, derived from the always-on CPU profiler's folded
        stacks: an io-thread sample whose leaf is the selector wait is
        idle; anything else is the loop doing work.  None when the
        profiling plane is off or no io samples landed yet."""
        prof = getattr(self, "_cpu_profiler", None)
        if prof is None:
            return None
        total = idle = 0
        for key, count in prof.snapshot().items():
            parts = key.split(";")
            if len(parts) < 3 or parts[1] != "art-io":
                continue
            total += count
            leaf = parts[-1]
            if ":select" in leaf or ":poll" in leaf:
                idle += count
        last_total, last_idle = self._io_duty_cursor
        self._io_duty_cursor = (total, idle)
        window = total - last_total
        if window <= 0:
            return None
        return 1.0 - (idle - last_idle) / window

    def _scale_stats(self) -> dict:
        from ant_ray_tpu._private import protocol  # noqa: PLC0415

        return {
            "table_rows": {
                "nodes": len(self._nodes),
                "actors": len(self._actors),
                "jobs": len(self._jobs),
                "objects": len(self._object_locations),
                "placement_groups": len(self._placement_groups),
                "metrics": len(self._metrics),
                "kv": len(self._kv),
                "tasks": self._task_state.stats().get("num_records", 0),
                "virtual_clusters": len(self._virtual_clusters),
            },
            "rings": {
                "task_events": len(self._task_events),
                "step_events": len(self._step_events),
                "span_events": len(self._span_events),
                "cpu_profile": len(self._cpu_profile),
                "pub_events": len(self._pub_events),
                "insight_events": len(self._insight_events),
            },
            "subscribers": self._sub_pollers,
            "sched": dict(self._sched_stats),
            "heartbeat": dict(self._hb_stats),
            # method -> [calls, handle_ns]: this process's server-side
            # dispatch→reply cost per RPC method (protocol.py).
            "handle": {m: list(v) for m, v in
                       protocol.handle_counters.items()},
            "io_loop_duty": self._io_loop_duty(),
        }

    async def _get_scale_stats(self, _payload):
        return self._scale_stats()

    async def _publish_self_metrics(self) -> None:
        """Fold the scale-stats snapshot into the metrics table as the
        ``art_gcs_*`` gauge set (scrapeable via /metrics like any other
        series).  Runs on the health-loop cadence; ~20 gauge upserts."""
        stats = self._scale_stats()
        node = (f"gcs-{self._ha.replica_id}"
                if self._ha is not None else "gcs")
        for table, rows in stats["table_rows"].items():
            await self._metric_record({
                "name": "art_gcs_table_rows", "type": "gauge",
                "value": float(rows),
                "tags": {"table": table, "node_id": node},
                "description": "GCS cluster-table row counts"})
        for ring, occupancy in stats["rings"].items():
            await self._metric_record({
                "name": "art_gcs_ring_len", "type": "gauge",
                "value": float(occupancy),
                "tags": {"ring": ring, "node_id": node},
                "description": "GCS bounded event-ring occupancy"})
        await self._metric_record({
            "name": "art_gcs_subscribers", "type": "gauge",
            "value": float(stats["subscribers"]),
            "tags": {"node_id": node},
            "description": "Parked pubsub long-pollers"})
        duty = stats["io_loop_duty"]
        if duty is not None:
            await self._metric_record({
                "name": "art_gcs_io_loop_duty", "type": "gauge",
                "value": round(duty, 4),
                "tags": {"node_id": node},
                "description": "GCS io-loop busy fraction (profiler-"
                               "derived, current window)"})

    # ------------------------------------------------------------- drain
    # (ref: the reference's DrainNode RPC + autoscaler drain protocol,
    #  gcs.proto DrainNodeRequest — here the announced-departure plane
    #  behind TPU maintenance events / preemption notices)

    def _apply_drain(self, info: NodeInfo, reason: str,
                     deadline: float) -> None:
        """Idempotent drain transition: publishes exactly once."""
        if info.draining:
            # Keep the earliest-announced deadline; a later notice
            # cannot push the departure time OUT.
            if deadline and (not info.drain_deadline
                             or deadline < info.drain_deadline):
                info.drain_deadline = deadline
            return
        info.draining = True
        info.drain_reason = reason
        info.drain_deadline = deadline
        self._save_node(info)
        self._publish("node", {"node_id": info.node_id, "alive": True,
                               "draining": True, "reason": reason,
                               "deadline": deadline,
                               "address": info.address})
        logger.info("node %s DRAINING (%s, deadline=%s)",
                    info.node_id.hex()[:8], reason or "unspecified",
                    deadline or "none")

    async def _drain_node(self, payload):
        """Put a node into DRAINING: schedulers skip it for new leases
        and bundle placements, Serve migrates its replicas, and Train
        controllers proactively checkpoint + relaunch gangs off it.
        The node stays ALIVE (its current work keeps running) until it
        actually departs."""
        info = self._nodes.get(payload["node_id"])
        if info is None or not info.alive:
            return False
        self._apply_drain(info, payload.get("reason", ""),
                          float(payload.get("deadline") or 0.0))
        return True

    async def _health_check_loop(self):
        cfg = global_config()
        period = cfg.heartbeat_period_s
        timeout = cfg.heartbeat_period_s * cfg.num_heartbeats_timeout
        self_metrics_every = max(1, int(round(2.0 / period)))
        ticks = 0
        while True:
            await asyncio.sleep(period)
            ticks += 1
            if ticks % self_metrics_every == 0:
                try:  # observability must never stall liveness judging
                    await self._publish_self_metrics()
                except Exception:  # noqa: BLE001 — best-effort gauges
                    pass
            if not self._leading():
                continue    # standbys observe, only the leader judges
            now = time.monotonic()
            for node_id, info in list(self._nodes.items()):
                # Nodes synced from the store while standing by have no
                # beat record yet — grant one from first sight.
                last = self._last_heartbeat.setdefault(node_id, now)
                if info.alive and now - last > timeout:
                    logger.warning("node %s missed heartbeats; marking dead",
                                   node_id.hex()[:8])
                    await self._on_node_death(node_id)

    async def _on_node_death(self, node_id: NodeID):
        info = self._nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        self._save_node(info)
        self._publish("node", {"node_id": node_id, "alive": False,
                               "address": info.address})
        self._expire_node_metrics(node_id)
        for oid, nodes in list(self._object_locations.items()):
            nodes.discard(node_id)
        for record in list(self._actors.values()):
            if record.node_id == node_id and record.state in (
                    ACTOR_ALIVE, ACTOR_PENDING, ACTOR_RESTARTING):
                await self._handle_actor_failure(record, "node died")

    # ----------------------------------------------- virtual clusters
    # Multi-tenant partitioning of the physical cluster (ant-fork
    # capability, ref: gcs_virtual_cluster.h:154 DivisibleCluster /
    # IndivisibleCluster; the unassigned remainder acts as the
    # PrimaryCluster).  Jobs bound to a VC schedule only on its nodes;
    # unbound jobs schedule only on unassigned nodes.

    def _assigned_node_ids(self) -> set:
        out: set = set()
        for record in self._virtual_clusters.values():
            out |= record["node_ids"]
        return out

    def _allowed_nodes_for_job(self, job_id) -> set | None:
        """Node-id set a job may use, or None for 'no restriction'
        (no VCs exist at all)."""
        if not self._virtual_clusters:
            return None
        vc_id = self._job_vc.get(job_id) if job_id is not None else None
        if vc_id is not None and vc_id in self._virtual_clusters:
            return set(self._virtual_clusters[vc_id]["node_ids"])
        alive = {n.node_id for n in self._nodes.values() if n.alive}
        return alive - self._assigned_node_ids()

    async def _create_virtual_cluster(self, payload):
        vc_id = payload["vc_id"]
        if vc_id in self._virtual_clusters:
            return {"error": f"virtual cluster {vc_id!r} exists"}
        node_ids = set(payload.get("node_ids") or [])
        num_nodes = payload.get("num_nodes")
        taken = self._assigned_node_ids()
        if num_nodes is not None and not node_ids:
            free = [n.node_id for n in self._nodes.values()
                    if n.alive and n.node_id not in taken]
            if len(free) < num_nodes:
                return {"error": f"only {len(free)} unassigned nodes "
                                 f"available, need {num_nodes}"}
            node_ids = set(free[:num_nodes])
        conflicts = node_ids & taken
        if conflicts:
            return {"error": "node(s) already assigned to another "
                             "virtual cluster"}
        bad = {n for n in node_ids
               if n not in self._nodes or not self._nodes[n].alive}
        if bad:
            return {"error": f"unknown or dead node id(s): "
                             f"{[n.hex()[:8] for n in bad]}"}
        self._virtual_clusters[vc_id] = {
            "node_ids": node_ids,
            "divisible": bool(payload.get("divisible", False)),
            "created_at": time.time(),
        }
        self._save_vcs()
        return {"vc_id": vc_id,
                "node_ids": [n.hex() for n in node_ids]}

    async def _remove_virtual_cluster(self, payload):
        removed = self._virtual_clusters.pop(payload["vc_id"], None)
        for job_id, vc in list(self._job_vc.items()):
            if vc == payload["vc_id"]:
                del self._job_vc[job_id]
        self._save_vcs()
        return removed is not None

    async def _update_virtual_cluster(self, payload):
        record = self._virtual_clusters.get(payload["vc_id"])
        if record is None:
            return {"error": "no such virtual cluster"}
        add = set(payload.get("add_nodes") or [])
        conflicts = add & (self._assigned_node_ids() - record["node_ids"])
        if conflicts:
            return {"error": "node(s) already assigned elsewhere"}
        bad = {n for n in add
               if n not in self._nodes or not self._nodes[n].alive}
        if bad:
            return {"error": f"unknown or dead node id(s): "
                             f"{[n.hex()[:8] for n in bad]}"}
        record["node_ids"] |= add
        record["node_ids"] -= set(payload.get("remove_nodes") or [])
        self._save_vcs()
        return {"node_ids": [n.hex() for n in record["node_ids"]]}

    async def _list_virtual_clusters(self, _payload):
        return {
            vc_id: {"node_ids": [n.hex() for n in r["node_ids"]],
                    "divisible": r["divisible"],
                    "jobs": [j.hex() for j, v in self._job_vc.items()
                             if v == vc_id]}
            for vc_id, r in self._virtual_clusters.items()
        }

    async def _set_job_virtual_cluster(self, payload):
        vc_id = payload.get("vc_id")
        if vc_id is None:
            self._job_vc.pop(payload["job_id"], None)
            self._save_vcs()
            return True
        if vc_id not in self._virtual_clusters:
            return {"error": f"no virtual cluster {vc_id!r}"}
        self._job_vc[payload["job_id"]] = vc_id
        self._save_vcs()
        return True

    async def _get_job_virtual_cluster(self, payload):
        allowed = self._allowed_nodes_for_job(payload["job_id"])
        return {
            "vc_id": self._job_vc.get(payload["job_id"]),
            "allowed_node_ids": (None if allowed is None
                                 else [n.hex() for n in allowed]),
        }

    # --------------------------------------------------- flow insight

    async def _insight_record(self, payload):
        self._insight_events.append(payload)
        return True

    async def _insight_get(self, payload):
        limit = int(payload.get("limit", 1000))
        events = list(self._insight_events)
        return events[-limit:]

    # ------------------------------------------------------ task events

    async def _task_events_add(self, payload):
        events = payload.get("events", ())
        self._task_events.extend(events)
        # Fold into the bounded state table AT INGESTION (one dict
        # upsert per event — benched by task_state_ingest_overhead_ns;
        # this path must stay cheap, see the export gate below).
        fold = self._task_state.apply
        for ev in events:
            fold(ev)
        dropped = payload.get("dropped")
        if dropped:
            self._task_events_dropped += int(dropped)
        if self._exporter is not None and \
                global_config().export_task_events:
            # Off by default, like the reference's per-source
            # enable_export_api_write gates: task events are the one
            # high-volume source, and recording each one costs ~40%% of
            # async task throughput on a small head.
            for ev in events:
                self._exporter.record("EXPORT_TASK",
                                      str(ev.get("event", "")).upper(),
                                      ev.get("task_id"), ev)
        return True

    async def _task_events_get(self, payload):
        payload = payload or {}
        limit = int(payload.get("limit", 50000))
        task_id = payload.get("task_id")
        events = list(self._task_events)
        if task_id is not None:
            events = [e for e in events if e.get("task_id") == task_id]
        if self._ha is not None and not payload.get("local_only"):
            # Sharded ring: merge every live replica's local slice
            # (producers spread their flushes across replicas).
            for peer_events in await self._ha.gather_ring(
                    "TaskEventsGet", payload):
                events.extend(peer_events)
            events.sort(key=lambda e: e.get("ts") or 0.0)
        return events[-limit:]

    # ---------------------------------------------- task state API
    # (ref: ray.util.state's state_aggregator path — list/summarize
    #  answered from the GCS-side folded table with server-side
    #  filtering; the client never pulls the raw event ring)

    def _state_stats(self) -> dict:
        return {"num_tasks_dropped": self._task_state.num_tasks_dropped,
                "task_events_dropped": self._task_events_dropped,
                **self._task_state.stats()}

    async def _merged_task_records(self,
                                   filters: dict) -> tuple[list, int, int]:
        """HA fan-in for the state API: this replica's records plus
        every live peer's (``local_only`` fan-out), merged with
        sticky-terminal semantics, THEN filtered — filtering per
        replica before the merge would let a ``state=RUNNING`` query
        resurface a task another replica knows FAILED.  Returns
        (records, dropped, events_dropped) with the drop counters
        summed across replicas — a clipped view stays visibly
        clipped after the merge."""
        from ant_ray_tpu._private.task_state import (  # noqa: PLC0415
            TaskStateTable,
            merge_public_records,
        )

        local = self._task_state.list(filters={}, limit=1 << 30)
        lists = [local["tasks"]]
        dropped = local["num_tasks_dropped"]
        events_dropped = self._task_events_dropped
        for reply in await self._ha.gather_ring(
                "ListTasks", {"limit": 1 << 30}):
            lists.append(reply.get("tasks"))
            dropped += reply.get("num_tasks_dropped", 0)
            events_dropped += reply.get("task_events_dropped", 0)
        merged = [r for r in merge_public_records(lists)
                  if TaskStateTable._matches(r, filters)]
        return merged, dropped, events_dropped

    async def _list_tasks(self, payload):
        payload = payload or {}
        filters = {k: payload.get(k)
                   for k in ("state", "name", "job_id", "actor_id",
                             "node_id")}
        limit = max(1, int(payload.get("limit", 1000)))
        if self._ha is not None and not payload.get("local_only"):
            records, dropped, events_dropped = \
                await self._merged_task_records(filters)
            # Offset-style continuation over the deterministically-
            # sorted merged view (the single-replica seq cursor cannot
            # span replicas); the token stays an opaque int either way.
            # Known HA-mode tradeoffs, acceptable at the bounded table
            # sizes (task_table_max_per_job): each page re-runs the
            # full fan-in (no cross-page snapshot), and GC between
            # pages can shift offsets — unlike the eviction-safe
            # single-replica cursor.
            offset = int(payload.get("token") or 0)
            page = records[offset:offset + limit]
            next_token = (offset + limit
                          if offset + limit < len(records) else None)
            return {"tasks": page, "next_token": next_token,
                    "num_tasks_dropped": dropped,
                    "task_events_dropped": events_dropped}
        reply = self._task_state.list(
            filters=filters,
            limit=limit,
            token=payload.get("token"))
        reply["task_events_dropped"] = self._task_events_dropped
        return reply

    async def _get_task(self, payload):
        attempts = self._task_state.get(payload["task_id"])
        if self._ha is not None and not payload.get("local_only"):
            from ant_ray_tpu._private.task_state import (  # noqa: PLC0415
                merge_public_records,
            )

            lists = [attempts]
            for reply in await self._ha.gather_ring(
                    "GetTask", {"task_id": payload["task_id"]}):
                if reply:
                    lists.append(reply.get("attempts"))
            attempts = sorted(merge_public_records(lists),
                              key=lambda r: r["attempt"])
        if not attempts:
            return None
        return {"task_id": payload["task_id"], "attempts": attempts,
                "stats": self._state_stats()}

    async def _summarize_tasks(self, payload):
        payload = payload or {}
        filters = {k: payload.get(k) for k in ("job_id", "node_id")}
        if self._ha is not None and not payload.get("local_only"):
            from ant_ray_tpu._private.task_state import (  # noqa: PLC0415
                summarize_public_records,
            )

            records, dropped, events_dropped = \
                await self._merged_task_records(filters)
            reply = summarize_public_records(records)
            reply["num_tasks_dropped"] = dropped
            reply["task_events_dropped"] = events_dropped
            return reply
        reply = self._task_state.summarize(filters=filters)
        reply["task_events_dropped"] = self._task_events_dropped
        return reply

    async def _list_jobs(self, _payload):
        return [
            {"job_id": job_id.hex(),
             "driver_address": info.get("driver_address", ""),
             "started_at": info.get("started_at")}
            for job_id, info in self._jobs.items()
        ]

    # ------------------------------------------------------ step events
    # (observability/step_profiler.py: batch-published per-step phase
    #  records, one bounded ring like task events)

    async def _step_events_add(self, payload):
        self._step_events.extend(payload.get("records", ()))
        return True

    async def _step_events_get(self, payload):
        payload = payload or {}
        limit = int(payload.get("limit", 20000))
        rank = payload.get("rank")
        records = list(self._step_events)
        if rank is not None:
            records = [r for r in records if r.get("rank") == rank]
        if self._ha is not None and not payload.get("local_only"):
            for peer_records in await self._ha.gather_ring(
                    "StepEventsGet", payload):
                records.extend(peer_records)
            records.sort(key=lambda r: r.get("ts") or 0.0)
        return records[-limit:]

    # ------------------------------------------------------ span events
    # (observability/tracing_plane.py: per-process flight recorders
    #  batch-publish sampled + force-sampled spans here; one bounded
    #  ring like step events)

    async def _span_events_add(self, payload):
        self._span_events.extend(payload.get("spans", ()))
        return True

    async def _span_events_get(self, payload):
        payload = payload or {}
        limit = int(payload.get("limit", 50000))
        trace_id = payload.get("trace_id")
        node_id = payload.get("node_id")
        errors_only = payload.get("errors_only")
        spans = list(self._span_events)
        if trace_id is not None:
            spans = [s for s in spans if s.get("trace_id") == trace_id]
        if node_id:
            spans = [s for s in spans
                     if str(s.get("node_id", "")).startswith(node_id)]
        if errors_only:
            spans = [s for s in spans if s.get("error")]
        if self._ha is not None and not payload.get("local_only"):
            for peer_spans in await self._ha.gather_ring(
                    "SpanEventsGet", payload):
                spans.extend(peer_spans)
            spans.sort(key=lambda s: s.get("ts") or 0.0)
        return spans[-limit:]

    # ---------------------------------------------------- cpu profiles
    # (observability/cpu_profiler.py: every process class publishes its
    #  folded-stack delta each publish period; one bounded ring like
    #  step/span events, sharded under HA and merged at query time)

    async def _cpu_profile_add(self, payload):
        self._cpu_profile.extend(payload.get("records", ()))
        return True

    async def _cpu_profile_get(self, payload):
        payload = payload or {}
        limit = int(payload.get("limit", 4000))
        node_id = payload.get("node_id")
        proc = payload.get("proc")
        since_ts = payload.get("since_ts")
        records = list(self._cpu_profile)
        if node_id:
            records = [r for r in records
                       if str(r.get("node_id", "")).startswith(node_id)]
        if proc:
            records = [r for r in records if r.get("proc") == proc]
        if since_ts is not None:
            records = [r for r in records
                       if (r.get("ts") or 0.0) >= float(since_ts)]
        if self._ha is not None and not payload.get("local_only"):
            for peer_records in await self._ha.gather_ring(
                    "CpuProfileGet", payload):
                records.extend(peer_records)
            records.sort(key=lambda r: r.get("ts") or 0.0)
        return records[-limit:]

    # -------------------------------------------------------- metrics
    # (ref: src/ray/stats/metric.h registry + the dashboard metrics
    #  agent python/ray/_private/metrics_agent.py — GCS holds the
    #  aggregated series; the dashboard renders Prometheus text)

    async def _metric_record(self, payload):
        """{"name","type","value","tags","description"} — counters
        accumulate, gauges overwrite, histograms keep running stats."""
        key = (payload["name"],
               tuple(sorted((payload.get("tags") or {}).items())))
        mtype = payload["type"]
        entry = self._metrics.get(key)
        if entry is None:
            entry = {"name": payload["name"], "type": mtype,
                     "tags": dict(payload.get("tags") or {}),
                     "description": payload.get("description", ""),
                     "value": 0.0, "count": 0, "sum": 0.0}
            self._metrics[key] = entry
        value = float(payload["value"])
        if mtype == "counter":
            entry["value"] += value
        elif mtype == "gauge":
            entry["value"] = value
        else:  # histogram: running count/sum + per-bucket tallies
            bounds = payload.get("boundaries")
            if bounds and "boundaries" not in entry:
                entry["boundaries"] = [float(b) for b in bounds]
                entry["buckets"] = [0] * len(entry["boundaries"])
            entry["count"] += 1
            entry["sum"] += value
            entry["value"] = value
            for i, le in enumerate(entry.get("boundaries", ())):
                if value <= le:
                    entry["buckets"][i] += 1
                    break               # cumulation happens at render
            # OpenMetrics exemplar: keep the latest per series — the
            # /metrics renderer links the histogram to a concrete
            # trace id (tracing_plane's rpc histograms send these).
            if payload.get("exemplar"):
                entry["exemplar"] = payload["exemplar"]
        self._metrics_dirty = True
        return True

    async def _metrics_get(self, _payload):
        return list(self._metrics.values())

    async def _metrics_expire(self, payload):
        """Drop series whose tags match ``match_tags`` (all pairs must
        match; ``name_prefix`` additionally narrows by metric name).
        The owners of per-entity gauges call this at teardown — a dead
        node's ``art_device_hbm_*`` or a removed replica's
        ``art_serve_breaker_state`` must not live in /metrics forever."""
        match = dict(payload.get("match_tags") or {})
        prefix = payload.get("name_prefix", "")
        if not match and not prefix:
            return 0
        doomed = [key for key, entry in self._metrics.items()
                  if (not prefix or entry["name"].startswith(prefix))
                  and all(entry["tags"].get(k) == v
                          for k, v in match.items())]
        for key in doomed:
            del self._metrics[key]
        if doomed:
            self._metrics_dirty = True
        return len(doomed)

    def _expire_node_metrics(self, node_id: NodeID) -> None:
        """Node-death hook: series tagged with the dead node's id (the
        agent's ``art_device_hbm_*`` publishes, any per-node gauges
        recorded into the table) are pruned immediately."""
        full, short = node_id.hex(), node_id.hex()[:12]
        doomed = [key for key, entry in self._metrics.items()
                  if entry["tags"].get("node_id") in (full, short)]
        for key in doomed:
            del self._metrics[key]
        if doomed:
            self._metrics_dirty = True

    # ------------------------------------------------------------- kv

    async def _kv_put(self, payload):
        key, value = payload["key"], payload["value"]
        overwrite = payload.get("overwrite", True)
        if not overwrite and key in self._kv:
            return False
        self._kv[key] = value
        self._persist("kv", key, value)
        return True

    async def _kv_get(self, payload):
        import pickle  # noqa: PLC0415

        key = payload["key"]
        value = self._kv.get(key)
        if self._ha is None or self._ha.is_leader_active():
            return value
        if payload.get("fence"):
            # Authoritative read-your-writes: ask the LEADER's
            # in-memory table.  Correct on every store backend — a
            # remote store's write-through is async (ack precedes
            # landing), so even a fenced store read could miss the
            # leader's latest acknowledged put; and the store, not the
            # synced cache, decides deletes (a deleted key must not
            # resurrect from sync lag).
            leader = self._ha.leader_addr()
            if leader:
                try:
                    return await self._clients.get(leader).call_async(
                        "KVGet", {"key": key}, timeout=5)
                except Exception:  # noqa: BLE001 — leader mid-death:
                    pass           # fall back to the fenced store read
            blob = await asyncio.to_thread(self._store.get, "kv", key)
            return pickle.loads(blob) if blob is not None else None
        if value is None:
            # Plain cache miss: best-effort freshness via the store (a
            # just-put key beats the sync period; a fence failure
            # raises typed StoreFenceError instead of serving stale).
            blob = await asyncio.to_thread(self._store.get, "kv", key)
            if blob is not None:
                value = pickle.loads(blob)
        return value

    async def _kv_del(self, payload):
        self._persist_del("kv", payload["key"])
        return self._kv.pop(payload["key"], None) is not None

    async def _kv_take(self, payload):
        """Atomic get-and-delete (one event-loop turn — no reader can
        interleave between the read and the removal).  The p2p mailbox
        protocol (xla_group.py send/recv) relies on this to make
        exactly one of {receiver-take, sender-withdraw} win."""
        value = self._kv.pop(payload["key"], None)
        if value is not None:
            self._persist_del("kv", payload["key"])
        return value

    async def _kv_keys(self, payload):
        prefix = payload.get("prefix", "")
        return [k for k in self._kv if k.startswith(prefix)]

    # ------------------------------------------------------------- jobs

    async def _register_job(self, payload):
        self._jobs[payload["job_id"]] = {
            "driver_address": payload.get("driver_address", ""),
            "started_at": time.time(),
        }
        self._persist("jobs", payload["job_id"].hex(),
                      (payload["job_id"], self._jobs[payload["job_id"]]))
        if self._exporter is not None:
            self._exporter.record("EXPORT_DRIVER_JOB", "STARTED",
                                  payload["job_id"],
                                  self._jobs[payload["job_id"]])
        return True

    # ------------------------------------------------------------- actors

    async def _create_actor(self, spec: ActorSpec):
        key = (spec.namespace, spec.name)
        if spec.name:
            existing_id = self._named_actors.get(key)
            if existing_id is not None:
                existing = self._actors.get(existing_id)
                if existing is not None and existing.state != ACTOR_DEAD:
                    return {"error": f"actor name {spec.name!r} already taken",
                            "existing_actor_id": existing_id}
        record = ActorRecord(spec=spec)
        self._actors[spec.actor_id] = record
        if spec.name:
            self._named_actors[key] = spec.actor_id
        self._save_actor(record)
        _spawn(self._schedule_actor(record))
        return {"ok": True}

    async def _schedule_actor(self, record: ActorRecord):
        try:
            await self._schedule_actor_inner(record)
        except Exception as e:  # noqa: BLE001 — never leave PENDING forever
            logger.exception("actor scheduling failed")
            record.state = ACTOR_DEAD
            record.death_reason = f"scheduling error: {e}"
            record.state_event.set()
            self._save_actor(record)

    async def _schedule_actor_inner(self, record: ActorRecord):
        spec = record.spec
        placement = spec.placement_resources or spec.resources
        start = time.monotonic()
        while True:
            # 30s without a feasible node kills the actor — unless an
            # autoscaler is alive, in which case the recorded demand may
            # provision one (give it the reference's 10-minute window).
            limit = 600.0 if self._has_live_autoscaler() else 30.0
            if time.monotonic() - start > limit:
                break
            strategy = getattr(spec, "scheduling_strategy", None)
            if spec.placement_group_id is not None:
                node = self._pg_bundle_node(
                    spec.placement_group_id,
                    spec.placement_group_bundle_index)
            elif strategy == "SPREAD":
                node = self._pick_node_spread(
                    placement,
                    self._allowed_nodes_for_job(spec.job_id),
                    spec.label_selector)
            elif isinstance(strategy, dict) and \
                    strategy.get("kind") == "node_affinity":
                # The pin must still respect every fence the other
                # placement paths enforce: virtual-cluster membership,
                # label selector, and capacity feasibility.
                allowed = self._allowed_nodes_for_job(spec.job_id)
                node = next(
                    (n for n in self._feasible_nodes(
                        placement, False, allowed, spec.label_selector)
                     if n.node_id.hex() == strategy["node_id"]), None)
                if node is None and not strategy.get("soft"):
                    record.state = ACTOR_DEAD
                    record.death_reason = (
                        "node-affinity target "
                        f"{strategy['node_id'][:12]} is not alive, not "
                        "in the job's virtual cluster, or cannot "
                        "satisfy the actor's demand")
                    record.state_event.set()
                    self._save_actor(record)
                    return
                if node is None:       # soft: fall back to DEFAULT
                    node = self._pick_node(
                        placement,
                        allowed=allowed,
                        label_selector=spec.label_selector)
            else:
                node = self._pick_node(
                    placement,
                    allowed=self._allowed_nodes_for_job(spec.job_id),
                    label_selector=spec.label_selector)
            if node is not None:
                record.node_id = node.node_id
                client = self._clients.get(node.address)
                try:
                    await client.call_async("StartActorWorker", spec,
                                            timeout=30)
                    return  # worker will report ALIVE via ActorStateUpdate
                except Exception as e:  # noqa: BLE001 — reschedule
                    logger.warning("actor %s placement on %s failed: %s",
                                   spec.actor_id.hex()[:8],
                                   node.node_id.hex()[:8], e)
            elif spec.placement_group_id is None:
                # Unplaceable actor: surface the shape to the autoscaler.
                self._record_demand(placement, spec.label_selector)
            await asyncio.sleep(0.5)
        record.state = ACTOR_DEAD
        record.death_reason = "no node with required resources"
        record.state_event.set()
        self._save_actor(record)

    @staticmethod
    def _labels_match(info: NodeInfo, selector: dict | None) -> bool:
        """Exact-match label selector (ref: LabelSelector,
        src/ray/common/scheduling/label_selector.h — equality terms)."""
        if not selector:
            return True
        return all(info.labels.get(k) == v for k, v in selector.items())

    def _init_sched_observatory(self) -> None:
        """Scheduler-scope observatory state.  Called from __init__,
        and lazily from _pick_node so scheduling-policy unit tests can
        exercise a bare ``object.__new__(GcsServer)`` with just
        ``_nodes`` populated."""
        # Scheduler scan width: how many node records each feasibility
        # scan walked — THE number that says lease cost is O(nodes).
        self._sched_stats = {"scans": 0, "scanned_nodes": 0,
                             "picks": 0, "pick_cache_hits": 0}
        # Sticky pack-pick cache: (resources, by_available) -> node_id
        # of the last grant target, re-VALIDATED against live state
        # before reuse (never trusted stale) — see _pick_node.
        self._pick_cache: dict[tuple, NodeID] = {}

    def _feasible_nodes(self, resources: dict[str, float],
                        by_available: bool,
                        allowed: set | None,
                        label_selector: dict | None) -> list[NodeInfo]:
        out = []
        self._sched_stats["scans"] += 1
        self._sched_stats["scanned_nodes"] += len(self._nodes)
        for info in self._nodes.values():
            if self._node_feasible(info, resources, by_available,
                                   allowed, label_selector):
                out.append(info)
        return out

    def _node_feasible(self, info: NodeInfo,
                       resources: dict[str, float],
                       by_available: bool,
                       allowed: set | None,
                       label_selector: dict | None) -> bool:
        """The per-node grantability predicate — one place, shared by
        the full feasibility scan and the pick-cache revalidation."""
        if not info.alive:
            return False
        if getattr(info, "disk_full", False):
            return False  # out-of-disk nodes take no new work
        if getattr(info, "draining", False):
            return False  # announced departures take no new work
        if allowed is not None and info.node_id not in allowed:
            return False
        if not self._labels_match(info, label_selector):
            return False
        view = (info.available_resources if by_available
                else info.total_resources)
        return all(view.get(k, 0.0) >= v for k, v in resources.items())

    @staticmethod
    def _utilization(info: NodeInfo) -> float:
        total = sum(info.total_resources.values()) or 1.0
        free = sum(info.available_resources.values())
        return 1.0 - free / total

    def _pick_node(self, resources: dict[str, float],
                   by_available: bool = True,
                   allowed: set | None = None,
                   label_selector: dict | None = None) -> NodeInfo | None:
        """Hybrid pack/spread policy (ref:
        src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h —
        the reference's DEFAULT): prefer the BUSIEST feasible node
        whose utilization stays under the threshold (packing keeps
        small tasks off idle accelerator nodes and lets the autoscaler
        drain them), and once every candidate is past the threshold,
        spread to the least-utilized.

        by_available=True matches against the (heartbeat-fed, possibly
        stale) availability view; by_available=False against total
        capacity — used to distinguish "busy right now" from "can never
        run" (ref: ClusterResourceScheduler feasibility vs availability).
        ``allowed`` restricts candidates (virtual-cluster membership);
        ``label_selector`` restricts to nodes advertising those labels
        (TPU generation / pod / worker-id).

        Scale fix (measured by benchmarks/scale_harness.py — the worst
        cliff at N=500 was O(nodes) feasibility scans per lease): the
        last pick per plain scheduling shape is cached and REVALIDATED
        against live state before reuse.  Packing semantics make the
        sticky pick natural — consecutive leases WANT the same busiest
        under-threshold node, and the GCS availability view only moves
        on heartbeats anyway, so a fresh scan in between returns the
        same node at O(nodes) cost.  The cache never serves a dead,
        draining, full, or over-threshold node (the revalidation is the
        same predicate the scan uses on that one node); shapes with a
        virtual-cluster or label restriction always take the full scan.
        Config-gated (``sched_pick_cache``) so the harness can measure
        the before/after curve.
        """
        try:
            self._sched_stats["picks"] += 1
        except AttributeError:  # bare unit-test construction
            self._init_sched_observatory()
            self._sched_stats["picks"] += 1
        cfg = global_config()
        threshold = cfg.hybrid_pack_threshold
        cache_key = None
        if cfg.sched_pick_cache and allowed is None \
                and not label_selector:
            cache_key = (tuple(sorted(resources.items())), by_available)
            cached_id = self._pick_cache.get(cache_key)
            if cached_id is not None:
                info = self._nodes.get(cached_id)
                if info is not None \
                        and self._node_feasible(info, resources,
                                                by_available, None, None) \
                        and self._utilization(info) <= threshold:
                    self._sched_stats["pick_cache_hits"] += 1
                    return info
                self._pick_cache.pop(cache_key, None)
        candidates = self._feasible_nodes(resources, by_available,
                                          allowed, label_selector)
        if not candidates:
            return None
        under = [n for n in candidates
                 if self._utilization(n) <= threshold]
        if under:
            # Pack: busiest first; node id tie-break for determinism.
            pick = max(under, key=lambda n: (self._utilization(n),
                                             n.node_id.hex()))
            if cache_key is not None:
                if len(self._pick_cache) >= 64:  # bounded: shapes churn
                    self._pick_cache.clear()
                self._pick_cache[cache_key] = pick.node_id
            return pick
        # All hot: spread to the least-utilized.
        return min(candidates, key=lambda n: (self._utilization(n),
                                              n.node_id.hex()))

    def _pick_node_spread(self, resources, allowed, label_selector,
                          exclude=None) -> NodeInfo | None:
        """SPREAD policy: round-robin over feasible nodes (ref:
        spread_scheduling_policy.h).  ``exclude`` drops the saturated
        requester (it asked to spill AWAY) unless it is the only
        candidate."""
        candidates = self._feasible_nodes(resources, True, allowed,
                                          label_selector)
        if not candidates:
            candidates = self._feasible_nodes(resources, False, allowed,
                                              label_selector)
        if exclude is not None and len(candidates) > 1:
            candidates = [n for n in candidates
                          if n.node_id != exclude]
        if not candidates:
            return None
        candidates.sort(key=lambda n: n.node_id.hex())
        self._spread_rr += 1
        return candidates[self._spread_rr % len(candidates)]

    def _pg_bundle_node(self, pg_id, bundle_index: int) -> NodeInfo | None:
        record = self._placement_groups.get(pg_id)
        if record is None or record["state"] != "CREATED":
            return None
        if not 0 <= bundle_index < len(record["bundle_nodes"]):
            raise ValueError(
                f"bundle index {bundle_index} out of range for group with "
                f"{len(record['bundle_nodes'])} bundles")
        return record["bundle_nodes"][bundle_index]

    async def _actor_state_update(self, payload):
        actor_id = payload["actor_id"]
        record = self._actors.get(actor_id)
        if record is None:
            return False
        record.state = payload["state"]
        record.address = payload.get("address", record.address)
        if payload.get("node_id") is not None:
            record.node_id = payload["node_id"]
        if record.state == ACTOR_DEAD:
            record.death_reason = payload.get("reason", "")
        record.state_event.set()
        record.state_event = asyncio.Event()
        self._save_actor(record)
        self._publish("actor_state", {
            "actor_id": record.spec.actor_id, "state": record.state,
            "address": record.address,
            "death_reason": record.death_reason})
        return True

    async def _list_actors(self, _payload):
        return [
            {
                "actor_id": r.spec.actor_id.hex(),
                "class_name": r.spec.class_name,
                "state": r.state,
                "address": r.address,
                "name": r.spec.name,
                # Where the actor runs (drain-plane consumers map
                # replicas/gang workers to draining nodes with this).
                "node_id": (r.node_id.hex()
                            if r.node_id is not None else None),
                "job_id": (r.spec.job_id.hex()
                           if r.spec.job_id is not None else None),
                "death_reason": r.death_reason,
            }
            for r in self._actors.values()
        ]

    async def _list_objects(self, _payload):
        return [
            {
                "object_id": oid.hex(),
                "locations": [nid.hex() for nid in nodes],
                "owner": self._object_meta.get(oid, {}).get("owner"),
                "callsite": self._object_meta.get(oid, {}).get(
                    "callsite"),
            }
            for oid, nodes in self._object_locations.items()
        ]

    async def _get_actor_info(self, payload):
        record = self._actors.get(payload["actor_id"])
        if record is None:
            return None
        return self._actor_info(record)

    def _actor_info(self, record: ActorRecord) -> dict:
        return {
            "actor_id": record.spec.actor_id,
            "state": record.state,
            "address": record.address,
            "node_id": record.node_id,
            "class_name": record.spec.class_name,
            "death_reason": record.death_reason,
            "name": record.spec.name,
        }

    async def _wait_actor_alive(self, payload):
        record = self._actors.get(payload["actor_id"])
        if record is None:
            return None
        deadline = time.monotonic() + payload.get("timeout", 30.0)
        while record.state not in (ACTOR_ALIVE, ACTOR_DEAD):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            event = record.state_event
            try:
                await asyncio.wait_for(event.wait(), remaining)
            except asyncio.TimeoutError:
                break
        return self._actor_info(record)

    async def _get_named_actor(self, payload):
        key = (payload.get("namespace", "default"), payload["name"])
        actor_id = self._named_actors.get(key)
        if actor_id is None:
            return None
        record = self._actors.get(actor_id)
        if record is None or record.state == ACTOR_DEAD:
            return None
        return self._actor_info(record)

    async def _kill_actor(self, payload):
        record = self._actors.get(payload["actor_id"])
        if record is None:
            return False
        no_restart = payload.get("no_restart", True)
        restartable = (not no_restart
                       and record.restarts_used < record.spec.max_restarts)
        if no_restart:
            record.spec.max_restarts = 0
        if record.node_id is not None:
            node = self._nodes.get(record.node_id)
            if node is not None and node.alive:
                client = self._clients.get(node.address)
                try:
                    await client.call_async(
                        "KillActorWorker",
                        {"actor_id": record.spec.actor_id}, timeout=10)
                except Exception:  # noqa: BLE001 — worker may be gone already
                    pass
        if restartable:
            # kill(no_restart=False): the worker death is a restartable
            # failure — the daemon's WorkerDied report (or this direct
            # call) drives the normal restart machinery, and subscribers
            # see RESTARTING, never a terminal DEAD.
            await self._handle_actor_failure(record,
                                             "killed via kill(no_restart"
                                             "=False)")
            return True
        record.state = ACTOR_DEAD
        record.death_reason = "killed via kill()"
        record.state_event.set()
        self._save_actor(record)
        self._publish("actor_state", {
            "actor_id": record.spec.actor_id, "state": ACTOR_DEAD,
            "address": "", "death_reason": record.death_reason})
        return True

    async def _worker_died(self, payload):
        if self._exporter is not None:
            self._exporter.record("EXPORT_WORKER", "DIED",
                                  payload.get("worker_id"), payload)
        actor_id = payload.get("actor_id")
        if actor_id is not None:
            record = self._actors.get(actor_id)
            if record is not None and record.state != ACTOR_DEAD:
                await self._handle_actor_failure(
                    record, payload.get("reason", "worker died"))
        return True

    async def _handle_actor_failure(self, record: ActorRecord, reason: str):
        if record.restarts_used < record.spec.max_restarts:
            record.restarts_used += 1
            record.state = ACTOR_RESTARTING
            record.address = ""
            record.state_event.set()
            record.state_event = asyncio.Event()
            logger.info("restarting actor %s (%d/%d): %s",
                        record.spec.actor_id.hex()[:8], record.restarts_used,
                        record.spec.max_restarts, reason)
            self._save_actor(record)
            self._publish("actor_state", {
                "actor_id": record.spec.actor_id,
                "state": ACTOR_RESTARTING, "address": "",
                "death_reason": ""})
            _spawn(self._schedule_actor(record))
        else:
            record.state = ACTOR_DEAD
            record.death_reason = reason
            record.state_event.set()
            record.state_event = asyncio.Event()
            self._save_actor(record)
            self._publish("actor_state", {
                "actor_id": record.spec.actor_id, "state": ACTOR_DEAD,
                "address": "", "death_reason": reason})

    # ------------------------------------------------------------- objects

    async def _object_location_add(self, payload):
        oid = payload["object_id"]
        self._object_locations.setdefault(oid, set()).add(
            payload["node_id"])
        # Optional attribution sidecar (additive payload keys): the
        # SEALING daemon knows the producer — pull-replica adds don't
        # resend it, so only fill what's missing.
        owner = payload.get("owner")
        if owner:
            meta = self._object_meta.setdefault(oid, {})
            meta.setdefault("owner", owner)
            if payload.get("callsite"):
                meta.setdefault("callsite", payload["callsite"])
        self._save_locations(oid)
        return True

    async def _object_location_remove(self, payload):
        oid = payload["object_id"]
        locs = self._object_locations.get(oid)
        if locs is not None:
            locs.discard(payload["node_id"])
            if not locs:
                del self._object_locations[oid]
                self._object_meta.pop(oid, None)
        self._save_locations(oid)
        return True

    async def _object_locations_get(self, payload):
        node_ids = self._object_locations.get(payload["object_id"], set())
        return [self._nodes[nid] for nid in node_ids
                if nid in self._nodes and self._nodes[nid].alive]

    async def _free_object(self, payload):
        oid = payload["object_id"]
        node_ids = self._object_locations.pop(oid, set())
        self._object_meta.pop(oid, None)
        self._save_locations(oid)
        for nid in node_ids:
            node = self._nodes.get(nid)
            if node is None or not node.alive:
                continue
            client = self._clients.get(node.address)
            try:
                await client.oneway_async("DeleteObject", {"object_id": oid})
            except Exception:  # noqa: BLE001
                pass
        return True

    # ------------------------------------------------- placement groups
    # (ref: GcsPlacementGroupManager + 2-phase bundle reservation,
    #  gcs_placement_group_scheduler.h)

    async def _create_placement_group(self, payload):
        record = {
            "pg_id": payload["pg_id"],
            "bundles": payload["bundles"],
            "strategy": payload["strategy"],
            "name": payload.get("name", ""),
            "job_id": payload.get("job_id"),
            "state": "PENDING",
            "bundle_nodes": [None] * len(payload["bundles"]),
            "reason": "",
            "bundle_selectors": payload.get("bundle_label_selectors"),
            "same_label": payload.get("same_label"),
            "same_label_groups": payload.get("same_label_groups"),
        }
        self._placement_groups[payload["pg_id"]] = record
        self._save_pg(record)
        if self._exporter is not None:
            self._exporter.record(
                "EXPORT_PLACEMENT_GROUP", "PENDING", payload["pg_id"],
                {"strategy": record["strategy"], "name": record["name"],
                 "bundles": record["bundles"]})
        _spawn(self._schedule_placement_group(record))
        return True

    def _plan_bundles(self, bundles, strategy, job_id=None,
                      bundle_selectors=None,
                      same_label=None,
                      same_label_groups=None) -> list[NodeInfo] | None:
        """Choose a node per bundle against the availability view; None if
        no valid assignment right now.  Candidates respect the job's
        virtual cluster.

        ``bundle_selectors``: optional per-bundle label selectors (exact
        match).  ``same_label``: a label key whose VALUE must be shared by
        every chosen node — the slice-affinity constraint ("all bundles on
        one tpu-pod-name") behind SlicePlacementGroup (ref:
        python/ray/util/tpu.py:52, bundle_label_selector).
        ``same_label_groups``: lists of bundle indices, each group pinned
        to ONE value of ``same_label`` and distinct groups to DISTINCT
        values — the multi-slice gang constraint (each slice's ranks
        co-located on one pod, different slices on different pods)."""
        allowed = self._allowed_nodes_for_job(job_id)
        alive = [n for n in self._nodes.values()
                 if n.alive and not getattr(n, "draining", False)
                 and (allowed is None or n.node_id in allowed)]
        if same_label is not None and same_label_groups:
            # Groups claim disjoint label values, so their node pools are
            # disjoint — planning them sequentially with independent
            # resource views is exact, not an approximation.  Greedy
            # first-fit value choice per group (deterministic order so
            # repeated attempts converge).
            values = sorted({n.labels.get(same_label) for n in alive
                             if n.labels.get(same_label) is not None})
            plan_by_index: dict = {}
            used_values: set = set()
            for group in same_label_groups:
                sub_bundles = [bundles[i] for i in group]
                sub_selectors = ([bundle_selectors[i] for i in group]
                                 if bundle_selectors else None)
                placed = False
                for value in values:
                    if value in used_values:
                        continue
                    pool = [n for n in alive
                            if n.labels.get(same_label) == value]
                    plan = self._plan_bundles_in(
                        pool, sub_bundles, strategy, sub_selectors)
                    if plan is not None:
                        used_values.add(value)
                        for i, node in zip(group, plan):
                            plan_by_index[i] = node
                        placed = True
                        break
                if not placed:
                    return None
            # Bundles outside every group (none for multi-slice PGs, but
            # the contract allows it) plan unconstrained.
            rest = [i for i in range(len(bundles))
                    if i not in plan_by_index]
            if rest:
                rest_plan = self._plan_bundles_in(
                    alive, [bundles[i] for i in rest], strategy,
                    [bundle_selectors[i] for i in rest]
                    if bundle_selectors else None)
                if rest_plan is None:
                    return None
                for i, node in zip(rest, rest_plan):
                    plan_by_index[i] = node
            return [plan_by_index[i] for i in range(len(bundles))]
        if same_label is not None:
            # Try each value-group of the shared label independently;
            # first group that fits wins.  Deterministic order so
            # repeated attempts converge.
            values = sorted({n.labels.get(same_label) for n in alive
                             if n.labels.get(same_label) is not None})
            for value in values:
                group = [n for n in alive
                         if n.labels.get(same_label) == value]
                plan = self._plan_bundles_in(
                    group, bundles, strategy, bundle_selectors)
                if plan is not None:
                    return plan
            return None
        return self._plan_bundles_in(alive, bundles, strategy,
                                     bundle_selectors)

    def _plan_bundles_in(self, alive, bundles, strategy,
                         bundle_selectors=None) -> list[NodeInfo] | None:
        remaining = {n.node_id: dict(n.available_resources) for n in alive}

        def selector_ok(node, index):
            if not bundle_selectors:
                return True
            return self._labels_match(node, bundle_selectors[index])

        def fits(node_id, bundle):
            return all(remaining[node_id].get(k, 0.0) >= v
                       for k, v in bundle.items())

        def take(node_id, bundle):
            for k, v in bundle.items():
                remaining[node_id][k] = remaining[node_id].get(k, 0.0) - v

        plan: list[NodeInfo] = []
        if strategy in ("STRICT_PACK", "PACK"):
            # try to fit everything on one node
            for node in alive:
                if not all(selector_ok(node, i)
                           for i in range(len(bundles))):
                    continue
                snapshot = dict(remaining[node.node_id])
                ok = True
                for bundle in bundles:
                    if fits(node.node_id, bundle):
                        take(node.node_id, bundle)
                    else:
                        ok = False
                        break
                remaining[node.node_id] = snapshot
                if ok:
                    return [node] * len(bundles)
            if strategy == "STRICT_PACK":
                return None
        # greedy per-bundle; SPREAD/STRICT_SPREAD prefer unused nodes
        used: set = set()
        for index, bundle in enumerate(bundles):
            candidates = sorted(
                alive, key=lambda n: (n.node_id in used,
                                      -sum(remaining[n.node_id].values())))
            chosen = None
            for node in candidates:
                if strategy == "STRICT_SPREAD" and node.node_id in used:
                    continue
                if not selector_ok(node, index):
                    continue
                if fits(node.node_id, bundle):
                    chosen = node
                    break
            if chosen is None:
                return None
            take(chosen.node_id, bundle)
            used.add(chosen.node_id)
            plan.append(chosen)
        return plan

    async def _schedule_placement_group(self, record):
        bundles = record["bundles"]
        deadline = time.monotonic() + 30.0
        while True:
            if time.monotonic() > deadline:
                # With a live autoscaler, provisioning (a GKE node pool
                # resize can take minutes) extends the wait — the gang
                # demand recorded below keeps driving it.
                if self._has_live_autoscaler():
                    deadline = time.monotonic() + \
                        global_config().infeasible_wait_s
                else:
                    break
            if record["state"] == "REMOVED":
                return
            plan = self._plan_bundles(
                bundles, record["strategy"], record.get("job_id"),
                bundle_selectors=record.get("bundle_selectors"),
                same_label=record.get("same_label"),
                same_label_groups=record.get("same_label_groups"))
            if plan is not None:
                prepared = []
                ok = True
                for index, (bundle, node) in enumerate(zip(bundles, plan)):
                    client = self._clients.get(node.address)
                    try:
                        reply = await client.call_async("PrepareBundle", {
                            "pg_id": record["pg_id"], "index": index,
                            "resources": bundle}, timeout=10)
                    except Exception:  # noqa: BLE001
                        reply = {"ok": False}
                    if reply.get("ok"):
                        prepared.append((index, node))
                    else:
                        ok = False
                        break
                # A concurrent RemovePlacementGroup may have fired while we
                # were preparing — or a node may die mid-commit.  Any such
                # case aborts and rolls back every prepared bundle.
                if ok and record["state"] != "REMOVED":
                    committed = True
                    for index, node in prepared:
                        client = self._clients.get(node.address)
                        try:
                            await client.call_async("CommitBundle", {
                                "pg_id": record["pg_id"], "index": index},
                                timeout=10)
                        except Exception:  # noqa: BLE001
                            committed = False
                            break
                        record["bundle_nodes"][index] = node
                    if committed and record["state"] != "REMOVED":
                        record["state"] = "CREATED"
                        self._drop_gang_demand(record)
                        self._save_pg(record)
                        return
                for index, node in prepared:  # roll back (2-phase abort)
                    record["bundle_nodes"][index] = None
                    client = self._clients.get(node.address)
                    try:
                        await client.call_async("ReturnBundle", {
                            "pg_id": record["pg_id"], "index": index},
                            timeout=10)
                    except Exception:  # noqa: BLE001
                        pass
                if record["state"] == "REMOVED":
                    return  # removal handler already dropped the store row
                self._save_pg(record)  # keep the store in sync w/ rollback
            else:
                # Unplaceable: surface the whole gang to the autoscaler
                # (a slice PG on an empty cluster is THE scale-up
                # trigger; without this the 120 retries starve silently).
                self._record_gang_demand(record)
                # Distinguish "busy now" from "never possible".
                totals = {n.node_id: dict(n.total_resources)
                          for n in self._nodes.values() if n.alive}
                feasible_nodes = len(totals)
                if record["strategy"] == "STRICT_SPREAD" and \
                        len(bundles) > feasible_nodes and \
                        not self._has_live_autoscaler():
                    record["state"] = "FAILED"
                    record["reason"] = (
                        f"STRICT_SPREAD needs {len(bundles)} nodes, "
                        f"cluster has {feasible_nodes}")
                    return
            await asyncio.sleep(0.25)
        record["state"] = "FAILED"
        record["reason"] = "timed out waiting for resources"

    async def _get_placement_group(self, payload):
        record = self._placement_groups.get(payload["pg_id"])
        if record is None:
            return None
        return {
            "state": record["state"],
            "strategy": record["strategy"],
            "reason": record["reason"],
            "bundle_nodes": [
                (n.address if n is not None else None)
                for n in record["bundle_nodes"]
            ],
            "bundles": record["bundles"],
        }

    async def _remove_placement_group(self, payload):
        record = self._placement_groups.get(payload["pg_id"])
        if record is None:
            return False
        record["state"] = "REMOVED"
        if self._exporter is not None:
            self._exporter.record("EXPORT_PLACEMENT_GROUP", "REMOVED",
                                  record["pg_id"], {})
        self._drop_gang_demand(record)
        # Persist the terminal state FIRST: a head crash mid-removal must
        # not resurrect a CREATED/PENDING record whose bundles the nodes
        # have already returned.
        self._persist_del("pgs", record["pg_id"].hex())
        for index, node in enumerate(record["bundle_nodes"]):
            if node is None:
                continue
            client = self._clients.get(node.address)
            try:
                await client.call_async("ReturnBundle", {
                    "pg_id": record["pg_id"], "index": index}, timeout=10)
            except Exception:  # noqa: BLE001
                pass
        del self._placement_groups[payload["pg_id"]]
        # Actors placed on the group die with it (ref: the reference's
        # remove_placement_group kills actors using the PG) — the
        # bundles' resources must actually come free, not stay held by
        # leases the dead reservation granted.  Kills run CONCURRENTLY:
        # a wedged node must not serialize the handler 10s per actor.
        doomed = [actor_rec for actor_rec in self._actors.values()
                  if actor_rec.state != ACTOR_DEAD
                  and actor_rec.spec.placement_group_id == payload["pg_id"]]

        async def _kill_quietly(actor_rec):
            try:
                await self._kill_actor({
                    "actor_id": actor_rec.spec.actor_id,
                    "no_restart": True})
            except Exception:  # noqa: BLE001 — actor already dying
                pass

        if doomed:
            await asyncio.gather(*[_kill_quietly(a) for a in doomed])
        return True

    async def _list_placement_groups(self, _payload):
        return {
            pg_id.hex(): {"state": r["state"], "strategy": r["strategy"],
                          "name": r["name"],
                          # hex, not the raw JobID — this reply feeds
                          # the dashboard's JSON endpoint directly
                          "job_id": (r["job_id"].hex()
                                     if r.get("job_id") is not None
                                     else None),
                          "bundles": r["bundles"]}
            for pg_id, r in self._placement_groups.items()
        }

    # ------------------------------------------------------------- placement

    async def _select_node(self, payload):
        resources = payload.get("resources", {})
        exclude = payload.get("exclude")
        selector = payload.get("label_selector")
        allowed = self._allowed_nodes_for_job(payload.get("job_id"))
        if payload.get("strategy") == "SPREAD":
            node = self._pick_node_spread(resources, allowed, selector,
                                          exclude=exclude)
            if node is None:
                self._record_demand(resources, selector)
            return node

        def _excluding(by_available: bool) -> NodeInfo | None:
            node = self._pick_node(resources, by_available, allowed,
                                   selector)
            if node is not None and node.node_id == exclude:
                others = [
                    n for n in self._nodes.values()
                    if n.alive and not getattr(n, "draining", False)
                    and n.node_id != exclude and (
                        allowed is None or n.node_id in allowed)
                    and self._labels_match(n, selector) and all(
                        (n.available_resources if by_available
                         else n.total_resources).get(k, 0) >= v
                        for k, v in resources.items())
                ]
                node = others[0] if others else None
            return node

        # Prefer a node that can run now; fall back to one that is merely
        # busy (the lease queues there) before declaring infeasibility.
        node = _excluding(True) or _excluding(False)
        if node is None:
            self._record_demand(resources, selector)
        return node

    # ---------------------------------------------- autoscaler surface
    # (ref: the v2 autoscaler's cluster-status input —
    # python/ray/autoscaler/v2/autoscaler.py:50; demand shapes come
    # from SelectNode misses the way the reference's come from the
    # resource-demand scheduler reports.)

    _DEMAND_TTL_S = 60.0

    def _record_demand(self, resources: dict, selector: dict | None):
        key = json.dumps([sorted(resources.items()),
                          sorted((selector or {}).items())])
        now = time.monotonic()
        entry = self._demands.get(key)
        if entry is None:
            # Prune here too — without an autoscaler polling
            # ResourceDemands, unique shapes would otherwise accumulate
            # in head memory for the cluster's lifetime.
            if len(self._demands) >= 256:
                self._prune_demands(now)
            if len(self._demands) >= 512:  # still full: drop the oldest
                oldest = min(self._demands,
                             key=lambda k: self._demands[k]["last_seen"])
                del self._demands[oldest]
            self._demands[key] = {
                "resources": dict(resources),
                "label_selector": dict(selector or {}),
                "count": 1, "first_seen": now, "last_seen": now}
        else:
            entry["count"] += 1
            entry["last_seen"] = now

    def _record_gang_demand(self, record) -> None:
        """An unplaceable placement group is a GANG demand: the
        autoscaler must provision a node set satisfying every bundle
        atomically (a whole TPU slice for slice PGs), not one bundle's
        worth of capacity (ref: gang resource requests in
        src/ray/gcs/gcs_autoscaler_state_manager.h — the cluster
        resource state reports pending gangs to the autoscaler).

        Keyed per PG — two pending identical-shape PGs are two gangs
        needing two node sets, so they must not merge into one demand
        entry.  The entry is dropped the moment the PG commits or is
        removed (_drop_gang_demand)."""
        selectors = record.get("bundle_selectors") or \
            [{} for _ in record["bundles"]]
        key = "gang:" + record["pg_id"].hex()
        now = time.monotonic()
        entry = self._demands.get(key)
        if entry is None:
            if len(self._demands) >= 256:
                self._prune_demands(now)
            if len(self._demands) >= 512:
                oldest = min(self._demands,
                             key=lambda k: self._demands[k]["last_seen"])
                del self._demands[oldest]
            self._demands[key] = {
                "pg_id": record["pg_id"].hex(),
                "bundles": [dict(b) for b in record["bundles"]],
                "bundle_selectors": [dict(s or {}) for s in selectors],
                "strategy": record["strategy"],
                "same_label": record.get("same_label"),
                "count": 1, "first_seen": now, "last_seen": now}
        else:
            entry["count"] += 1
            entry["last_seen"] = now

    def _drop_gang_demand(self, record) -> None:
        self._demands.pop("gang:" + record["pg_id"].hex(), None)

    def _prune_demands(self, now: float) -> None:
        for key in [k for k, e in self._demands.items()
                    if now - e["last_seen"] > self._DEMAND_TTL_S]:
            del self._demands[key]

    async def _resource_demands(self, _payload):
        now = time.monotonic()
        self._prune_demands(now)
        out = []
        for e in self._demands.values():
            common = {"count": e["count"],
                      "age_s": now - e["first_seen"],
                      "idle_s": now - e["last_seen"]}
            if "bundles" in e:
                out.append({"pg_id": e.get("pg_id"),
                            "bundles": e["bundles"],
                            "bundle_selectors": e["bundle_selectors"],
                            "strategy": e["strategy"],
                            "same_label": e["same_label"], **common})
            else:
                out.append({"resources": e["resources"],
                            "label_selector": e["label_selector"],
                            **common})
        return out

    async def _autoscaler_heartbeat(self, _payload):
        self._autoscaler_seen = time.monotonic()
        return True

    async def _autoscaling_enabled(self, _payload):
        return self._has_live_autoscaler()

    def _has_live_autoscaler(self) -> bool:
        return (self._autoscaler_seen is not None
                and time.monotonic() - self._autoscaler_seen < 30.0)

    async def _cluster_resources(self, _payload):
        totals: dict[str, float] = {}
        for info in self._nodes.values():
            # Draining nodes are excluded from BOTH capacity views: a
            # gang sized by totals that include an announced departure
            # would be unplaceable by the time it reserves.
            if info.alive and not getattr(info, "draining", False):
                for k, v in info.total_resources.items():
                    totals[k] = totals.get(k, 0.0) + v
        return totals

    async def _available_resources(self, _payload):
        totals: dict[str, float] = {}
        for info in self._nodes.values():
            # A draining node's capacity is unleaseable — reporting it
            # as available would make elastic policies size gangs the
            # scheduler can never place.
            if info.alive and not getattr(info, "draining", False):
                for k, v in info.available_resources.items():
                    totals[k] = totals.get(k, 0.0) + v
        return totals


def main():  # pragma: no cover — exercised via subprocess in tests
    import argparse
    import signal

    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, required=True)
    parser.add_argument("--monitor-pid", type=int, default=0,
                        help="exit when this process disappears")
    parser.add_argument("--store", default="",
                        help="sqlite path for durable tables (restart-"
                             "resync; empty = in-memory only)")
    parser.add_argument("--export-dir", default="",
                        help="directory for export-event JSONL files "
                             "(empty = export pipeline disabled)")
    parser.add_argument("--ha-replica-id", default="",
                        help="join the replicated control plane as this "
                             "replica (requires --store shared with the "
                             "other replicas); the lease decides the "
                             "leader, standbys serve follower reads")
    args = parser.parse_args()

    logging.basicConfig(
        level=global_config().log_level,
        format="[gcs %(levelname)s %(asctime)s] %(message)s")
    server = GcsServer(port=args.port, store_path=args.store or None,
                       export_dir=args.export_dir or None,
                       ha_replica_id=args.ha_replica_id or None)
    server.start()
    print(f"GCS_READY {server.address}", flush=True)

    stop = False

    def _term(*_a):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop:
        time.sleep(0.2)
        if args.monitor_pid and not os.path.exists(
                f"/proc/{args.monitor_pid}"):
            logger.warning("monitored pid %d gone; exiting", args.monitor_pid)
            break
    server.stop(graceful=False)
    # Skip interpreter teardown: daemon threads may hold the io loop and
    # sys.exit would wait on finalizers; the tables are flushed above.
    os._exit(0)


if __name__ == "__main__":
    main()
