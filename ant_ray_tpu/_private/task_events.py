"""Task event buffer: per-process buffering of task lifecycle events,
flushed in batches to the GCS aggregator.

Mirror of the reference's TaskEventBuffer (ref:
src/ray/core_worker/task_event_buffer.h — workers buffer status-change
events and periodically flush to the GCS task-event aggregator; the
timeline / state API read the aggregate).  The record() hot path
buffers compact tuples; flush expands them into the wire dicts (once
per batch, off the per-call path):

    {"task_id", "name", "event", "ts", "pid", "node_id", "worker",
     "parent_task_id", "actor_id", "attempt", "job_id", "error?",
     "trace_id?"}

``event`` ∈ {submitted, started, finished, failed}.  Flushes ride one
oneway RPC per batch (size- or age-triggered from the record path plus
an atexit drain — no dedicated thread on the hot path).  The executing
task's id is kept in a contextvar so nested submissions record their
parent, giving the timeline its span tree without a full OTel stack.

Loss accounting: a batch whose send raises is requeued ONCE (bounded —
the buffer must not grow without limit against a dead GCS); a batch
that fails twice is dropped and counted, and the drop count rides the
next successful flush (``dropped`` payload key) into the GCS
``task_events_dropped`` stat the state API reports — a lossy task view
is visible, never silent.
"""

from __future__ import annotations

import atexit
import contextvars
import os
import threading
import time

_MAX_BUFFER = 512
# A failed batch is requeued once if it fits this bound; combined with
# _MAX_BUFFER the buffer holds at most 2 batches against a dead GCS.
_MAX_REQUEUE = _MAX_BUFFER
_FLUSH_AGE_S = 1.0
# Terminal events (finished/failed) kept for replay after a GCS-replica
# failover: the replica that ingested them may die with its ring, and a
# FAILED that un-happens is the one loss the state API must never show.
# Bounded — only the recent tail replays; dedup is the state table's
# sticky-terminal fold.
_TERMINAL_TAIL = 256

current_task = contextvars.ContextVar("art_current_task", default=None)

# Per-process constants, hoisted off the record() hot path.
_PID = os.getpid()
_NODE_ID = os.environ.get("ART_NODE_ID", "")


class TaskEventBuffer:
    def __init__(self):
        self._events: list[dict] = []
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._lock = make_lock("task_events.buffer")
        self._last_flush = time.monotonic()
        self._registered = False
        self._atexit_registered = False
        self._flusher: threading.Thread | None = None
        self._retry: list[dict] | None = None  # one requeued batch
        self.dropped_total = 0                 # lifetime local drops
        self._dropped_unreported = 0           # delta not yet at the GCS
        from collections import deque  # noqa: PLC0415

        # Recent terminal events + the GCS ring epoch they were last
        # published under (see module constant).  When the router's
        # ring epoch moves (replica died / set changed), the next flush
        # prepends this tail so terminal states survive the failover.
        self._terminal_tail: deque = deque(maxlen=_TERMINAL_TAIL)
        self._ring_epoch_seen = 0

    def record(self, runtime, *, task_id: str, name: str, event: str,
               actor_id: str | None = None,
               parent_task_id: str | None = None,
               attempt: int = 0, error: str | None = None) -> None:
        # Hot path: the buffer holds compact TUPLES; the wire dicts are
        # built at flush time (amortized once per batch).  This runs 3x
        # per task cluster-wide — a 13-key dict literal per event is
        # measurable control-plane tax at 10k calls/s.
        job_id = getattr(runtime, "job_id", None)
        ctx = _trace_current_sampled()
        entry = (
            task_id, name, event, time.time(),
            getattr(runtime, "address", ""), actor_id,
            parent_task_id or current_task.get(),
            # Execution attempt: lets span derivation salt ids so a
            # retried task's spans never collide with the original run.
            attempt,
            # Job membership: the GCS state table's GC policy is
            # per-job, and ListTasks filters on it.
            job_id.hex() if job_id is not None else None,
            error[:512] if error is not None else None,
            # Sampled requests link their task records to the trace —
            # `art trace <id>` and GetTask meet in the middle.
            ctx.trace_id if ctx is not None else None,
        )
        flush_now = False
        register = False
        with self._lock:
            self._events.append(entry)
            if event == "finished" or event == "failed":
                self._terminal_tail.append(entry)
            now = time.monotonic()
            if len(self._events) >= _MAX_BUFFER or \
                    now - self._last_flush > _FLUSH_AGE_S:
                flush_now = True
            if not self._registered:  # decide under the lock — two
                self._registered = True  # first-recording threads must
                register = True          # not double-start the flusher
        if flush_now:
            self.flush()
        if register:
            if not self._atexit_registered:
                self._atexit_registered = True
                atexit.register(self.flush)
            # Periodic flusher: without it, the last events of a
            # long-lived worker (e.g. "finished" for its final task)
            # would sit buffered until the next record or process exit.
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="art-task-events")
            self._flusher.start()

    def _flush_loop(self) -> None:
        while True:
            time.sleep(_FLUSH_AGE_S)
            if _runtime() is None:
                # Worker disconnected (or events disabled): exit
                # instead of spinning no-op forever.  Clearing
                # _registered lets the next record() — e.g. after
                # art.shutdown()/art.init() — start a fresh flusher.
                with self._lock:
                    self._registered = False
                    self._flusher = None
                return
            self.flush()

    @staticmethod
    def _expand(entry) -> dict:
        """Compact buffer tuple -> the wire/GCS event dict (requeued
        batches are already expanded and pass through)."""
        if isinstance(entry, dict):
            return entry
        (task_id, name, event, ts, worker, actor_id, parent, attempt,
         job_id, error, trace_id) = entry
        out = {
            "task_id": task_id, "name": name, "event": event,
            "ts": ts, "pid": _PID, "node_id": _NODE_ID,
            "worker": worker, "actor_id": actor_id,
            "parent_task_id": parent, "attempt": attempt,
            "job_id": job_id,
        }
        if error is not None:
            out["error"] = error
        if trace_id is not None:
            out["trace_id"] = trace_id
        return out

    def flush(self) -> None:
        # The runtime is resolved per flush — a captured one would
        # outlive art.shutdown()/art.init() and drain this shared
        # buffer into the previous cluster's dead GCS.
        runtime = _runtime()
        if runtime is None:
            return
        # Ring-failover replay: the GCS router bumps ring_epoch when
        # the replica set changes (a replica died — possibly with this
        # producer's ingested events in its ring).  Replaying the
        # terminal tail costs one bounded batch; the GCS fold dedups.
        epoch = getattr(
            getattr(runtime, "_gcs", None), "ring_epoch", 0)
        replay: list[dict] = []
        prev_epoch_seen = None
        with self._lock:
            if epoch != self._ring_epoch_seen:
                prev_epoch_seen = self._ring_epoch_seen
                self._ring_epoch_seen = epoch
                replay = list(self._terminal_tail)
            if not self._events and self._retry is None \
                    and not self._dropped_unreported and not replay:
                return
            batch, self._events = self._events, []
            retry, self._retry = self._retry, None
            # Pop-and-zero under the lock: a concurrent flush (flusher
            # thread + a record()-triggered one) must not read the same
            # delta and double-report it to the GCS.
            dropped, self._dropped_unreported = \
                self._dropped_unreported, 0
            self._last_flush = time.monotonic()
        expand = self._expand
        batch = [expand(e) for e in batch]
        payload = {"events": [expand(e) for e in replay]
                   + (retry or []) + batch}
        if dropped:
            payload["dropped"] = dropped
        try:
            if replay:
                # A replay batch is the durability mechanism itself —
                # send it ACKNOWLEDGED (bounded timeout) rather than
                # fire-and-forget: a oneway's failure is swallowed
                # inside the async send, which would mark the epoch
                # seen while the tail never landed.  Failure lands in
                # the except below, which rewinds the epoch mark.
                call = getattr(getattr(runtime, "_gcs", None),
                               "call", None)
                if call is not None:
                    call("TaskEventsAdd", payload, timeout=2)
                else:        # bare fake/legacy runtime: best effort
                    runtime._send_oneway(runtime.gcs_address,
                                         "TaskEventsAdd", payload)
            else:
                runtime._send_oneway(runtime.gcs_address,
                                     "TaskEventsAdd", payload)
        except Exception:  # noqa: BLE001 — observability is best-effort
            with self._lock:
                # A replay that never left rewinds the epoch mark so
                # the next flush tries it again (the tail itself is
                # never consumed — it lives until overwritten).
                if prev_epoch_seen is not None:
                    self._ring_epoch_seen = prev_epoch_seen
                # The popped batch is NOT silently lost: requeue it
                # once under the bound; the already-retried part and
                # anything over the bound is dropped AND counted.
                newly_dropped = len(retry or [])
                if batch and len(batch) <= _MAX_REQUEUE \
                        and self._retry is None:
                    self._retry = batch
                else:
                    newly_dropped += len(batch)
                if newly_dropped:
                    self.dropped_total += newly_dropped
                    self._dropped_unreported += newly_dropped
                if dropped:   # the popped delta never reached the GCS
                    self._dropped_unreported += dropped


_buffer = TaskEventBuffer()


def _trace_current_sampled():
    """Lazy-bound (the tracing plane imports config, not this module):
    the first call replaces this indirection with the real accessor —
    record() runs 3x per task, and a per-call ``from ... import`` is
    measurable at 10k calls/s."""
    global _trace_current_sampled
    from ant_ray_tpu.observability.tracing_plane import (  # noqa: PLC0415
        current_sampled,
    )

    _trace_current_sampled = current_sampled
    return current_sampled()


_get_config = _worker = None


def _runtime():
    # Same lazy-bind: resolve the accessors once (global_worker IS the
    # process singleton; the config OBJECT is swapped by api.init, so
    # only the accessor function may be cached).
    global _get_config, _worker
    if _get_config is None:
        from ant_ray_tpu._private.config import global_config  # noqa: PLC0415
        from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

        _get_config = global_config
        _worker = global_worker
    if not _get_config().enable_task_events:
        return None
    if not _worker.connected:
        return None
    runtime = _worker.runtime
    return runtime if hasattr(runtime, "_send_oneway") else None


def record(task_id: str, name: str, event: str, *,
           actor_id: str | None = None,
           parent_task_id: str | None = None,
           attempt: int = 0, error: str | None = None) -> None:
    runtime = _runtime()
    if runtime is None:
        return
    _buffer.record(runtime, task_id=task_id, name=name, event=event,
                   actor_id=actor_id, parent_task_id=parent_task_id,
                   attempt=attempt, error=error)


def flush() -> None:
    _buffer.flush()
