"""Task event buffer: per-process buffering of task lifecycle events,
flushed in batches to the GCS aggregator.

Mirror of the reference's TaskEventBuffer (ref:
src/ray/core_worker/task_event_buffer.h — workers buffer status-change
events and periodically flush to the GCS task-event aggregator; the
timeline / state API read the aggregate).  Events here are plain dicts:

    {"task_id", "name", "event", "ts", "pid", "node_id", "worker",
     "parent_task_id", "actor_id"}

``event`` ∈ {submitted, started, finished, failed}.  Flushes ride one
oneway RPC per batch (size- or age-triggered from the record path plus
an atexit drain — no dedicated thread on the hot path).  The executing
task's id is kept in a contextvar so nested submissions record their
parent, giving the timeline its span tree without a full OTel stack.
"""

from __future__ import annotations

import atexit
import contextvars
import os
import threading
import time

_MAX_BUFFER = 512
_FLUSH_AGE_S = 1.0

current_task = contextvars.ContextVar("art_current_task", default=None)

# Per-process constants, hoisted off the record() hot path.
_PID = os.getpid()
_NODE_ID = os.environ.get("ART_NODE_ID", "")


class TaskEventBuffer:
    def __init__(self):
        self._events: list[dict] = []
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._lock = make_lock("task_events.buffer")
        self._last_flush = time.monotonic()
        self._registered = False
        self._flusher: threading.Thread | None = None

    def record(self, runtime, *, task_id: str, name: str, event: str,
               actor_id: str | None = None,
               parent_task_id: str | None = None,
               attempt: int = 0) -> None:
        entry = {
            "task_id": task_id, "name": name, "event": event,
            "ts": time.time(), "pid": _PID,
            "node_id": _NODE_ID,
            "worker": getattr(runtime, "address", ""),
            "actor_id": actor_id,
            "parent_task_id": parent_task_id or current_task.get(),
            # Execution attempt: lets span derivation salt ids so a
            # retried task's spans never collide with the original run.
            "attempt": attempt,
        }
        flush_now = False
        register = False
        with self._lock:
            self._events.append(entry)
            now = time.monotonic()
            if len(self._events) >= _MAX_BUFFER or \
                    now - self._last_flush > _FLUSH_AGE_S:
                flush_now = True
            if not self._registered:  # decide under the lock — two
                self._registered = True  # first-recording threads must
                register = True          # not double-start the flusher
        if flush_now:
            self.flush()
        if register:
            atexit.register(self.flush)
            # Periodic flusher: without it, the last events of a
            # long-lived worker (e.g. "finished" for its final task)
            # would sit buffered until the next record or process exit.
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="art-task-events")
            self._flusher.start()

    def _flush_loop(self) -> None:
        while True:
            time.sleep(_FLUSH_AGE_S)
            self.flush()

    def flush(self) -> None:
        # The runtime is resolved per flush — a captured one would
        # outlive art.shutdown()/art.init() and drain this shared
        # buffer into the previous cluster's dead GCS.
        runtime = _runtime()
        if runtime is None:
            return
        with self._lock:
            if not self._events:
                return
            batch, self._events = self._events, []
            self._last_flush = time.monotonic()
        try:
            runtime._send_oneway(runtime.gcs_address, "TaskEventsAdd",
                                 {"events": batch})
        except Exception:  # noqa: BLE001 — observability is best-effort
            pass


_buffer = TaskEventBuffer()


def _runtime():
    from ant_ray_tpu._private.config import global_config  # noqa: PLC0415
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    if not global_config().enable_task_events:
        return None
    if not global_worker.connected:
        return None
    runtime = global_worker.runtime
    return runtime if hasattr(runtime, "_send_oneway") else None


def record(task_id: str, name: str, event: str, *,
           actor_id: str | None = None,
           parent_task_id: str | None = None,
           attempt: int = 0) -> None:
    runtime = _runtime()
    if runtime is None:
        return
    _buffer.record(runtime, task_id=task_id, name=name, event=event,
                   actor_id=actor_id, parent_task_id=parent_task_id,
                   attempt=attempt)


def flush() -> None:
    _buffer.flush()
