"""Dashboard head process: REST state API, Prometheus /metrics exporter,
and the job-submission server.

Capability mirror of the reference's dashboard head + job manager
(ref: python/ray/dashboard/head.py:49, dashboard/modules/job/
job_manager.py:62, _private/metrics_agent.py Prometheus export), as one
aiohttp process colocated with the head node.  Endpoints:

    GET  /api/nodes | /api/actors | /api/placement_groups | /api/objects
    GET  /api/tasks | /api/tasks/summary | /api/memory
    GET  /api/cluster_status | /api/export_events | /api/ha
    GET  /api/scale                       per-subsystem head cost counters
    GET  /metrics                         (Prometheus text format)
    POST /api/profile                     {node_id?, duration_s} → XLA trace
    POST /api/jobs                        {entrypoint, runtime_env, ...}
    GET  /api/jobs            /api/jobs/{id}   /api/jobs/{id}/logs
    POST /api/jobs/{id}/stop

Jobs are driver subprocesses launched with ART_ADDRESS pointing at this
cluster (the reference's job supervisor pattern without the wrapper
actor — the dashboard process owns supervision).
"""

from __future__ import annotations

import asyncio
import os
import signal
import subprocess
import threading
import time
import uuid

from ant_ray_tpu._private.protocol import ClientPool


class JobManager:
    """Tracks driver subprocesses (ref: job_manager.py:62)."""

    def __init__(self, gcs_address: str, session_dir: str):
        self._gcs_address = gcs_address
        self._session_dir = session_dir
        self._jobs: dict[str, dict] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        # aiohttp dispatches handlers onto executor threads — every
        # _jobs/_procs mutation must hold this.
        self._lock = threading.Lock()

    def submit(self, entrypoint: str, runtime_env: dict | None = None,
               submission_id: str | None = None,
               metadata: dict | None = None) -> str:
        from ant_ray_tpu._private.runtime_env import (  # noqa: PLC0415
            ensure_framework_on_pythonpath)

        job_id = submission_id or f"art-job-{uuid.uuid4().hex[:10]}"
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id} already exists")
            # reserve the id before the (slow) spawn so a concurrent
            # duplicate submit can't double-launch
            self._jobs[job_id] = self._record(job_id, entrypoint,
                                              "PENDING",
                                              metadata=metadata)
        log_path = os.path.join(self._session_dir, "logs",
                                f"job-{job_id}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        from ant_ray_tpu._private import services  # noqa: PLC0415

        # Job drivers are user code — they may run accelerator work, so
        # restore the TPU-plugin trigger the control-plane env stashed.
        env = services.accelerator_env(dict(os.environ))
        env["ART_ADDRESS"] = self._gcs_address
        # Drivers must be able to import the framework even when it is
        # run from a checkout rather than pip-installed.
        ensure_framework_on_pythonpath(env)
        renv = runtime_env or {}
        env.update({str(k): str(v)
                    for k, v in (renv.get("env_vars") or {}).items()})
        cwd = renv.get("working_dir") or None
        log_file = open(log_path, "ab")
        try:
            proc = subprocess.Popen(
                entrypoint, shell=True, env=env, cwd=cwd,
                stdout=log_file, stderr=subprocess.STDOUT,
                start_new_session=True)
        except OSError as e:
            log_file.close()
            with self._lock:
                self._jobs[job_id].update(status="FAILED",
                                          message=str(e))
            return job_id
        log_file.close()
        with self._lock:
            self._procs[job_id] = proc
            self._jobs[job_id].update(status="RUNNING")
        return job_id

    @staticmethod
    def _record(job_id, entrypoint, status, message="", metadata=None):
        return {"submission_id": job_id, "entrypoint": entrypoint,
                "status": status, "message": message,
                "metadata": metadata or {},
                "start_time": time.time(), "end_time": None}

    def _refresh_locked(self, job_id: str):
        job = self._jobs.get(job_id)
        proc = self._procs.get(job_id)
        if job is None or proc is None or job["status"] not in (
                "RUNNING", "STOPPING"):
            return
        code = proc.poll()
        if code is None:
            return
        job["end_time"] = time.time()
        if job["status"] == "STOPPING":
            job["status"] = "STOPPED"
        elif code == 0:
            job["status"] = "SUCCEEDED"
        else:
            job["status"] = "FAILED"
            job["message"] = f"driver exited with code {code}"

    def get(self, job_id: str) -> dict | None:
        with self._lock:
            self._refresh_locked(job_id)
            job = self._jobs.get(job_id)
            return dict(job) if job else None

    def list(self) -> list[dict]:
        with self._lock:
            for jid in list(self._jobs):
                self._refresh_locked(jid)
            return [dict(j) for j in self._jobs.values()]

    def stop(self, job_id: str) -> bool:
        with self._lock:
            job = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
            if job is None or proc is None or proc.poll() is not None:
                return False
            job["status"] = "STOPPING"
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            proc.terminate()
        return True

    def logs(self, job_id: str) -> str:
        path = os.path.join(self._session_dir, "logs",
                            f"job-{job_id}.log")
        try:
            with open(path, "r", errors="replace") as f:
                return f.read()
        except FileNotFoundError:
            return ""

    def shutdown(self):
        with self._lock:
            procs = list(self._procs.values())
        for proc in procs:
            if proc.poll() is None:
                try:
                    os.killpg(proc.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    proc.terminate()


def _escape_label(value) -> str:
    """Prometheus exposition escaping: backslash, quote, newline."""
    return (str(value).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _prometheus_text(series: list[dict], exemplars: bool = False) -> str:
    """Render the GCS metrics table in Prometheus exposition format.

    ``exemplars=True`` renders OpenMetrics exemplar suffixes on
    histogram bucket lines — legal ONLY in the OpenMetrics exposition
    format (the /metrics handler enables it when the scraper's Accept
    header negotiates ``application/openmetrics-text``; classic
    text-format parsers would fail the whole scrape on the `#`)."""
    lines = []
    seen_headers = set()
    for s in series:
        name = s["name"].replace("-", "_").replace(".", "_")
        if name not in seen_headers:
            seen_headers.add(name)
            if s.get("description"):
                help_text = (str(s["description"])
                             .replace("\\", r"\\").replace("\n", r"\n"))
                lines.append(f"# HELP {name} {help_text}")
            ptype = {"counter": "counter", "gauge": "gauge",
                     "histogram": "histogram"}.get(s["type"], "untyped")
            lines.append(f"# TYPE {name} {ptype}")
        pairs = [f'{k}="{_escape_label(v)}"'
                 for k, v in sorted(s.get("tags", {}).items())]
        label = f"{{{','.join(pairs)}}}" if pairs else ""
        if s["type"] == "histogram":
            # Cumulative buckets + the mandatory +Inf bucket (== count).
            # The latest exemplar (OpenMetrics: `# {trace_id="..."} v ts`)
            # is attached to the first bucket its value fits — a slow
            # histogram links straight to a concrete trace id.
            exemplar = s.get("exemplar") if exemplars else None
            ex_text = ""
            if exemplar:
                ex_pairs = ",".join(
                    f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(
                        (exemplar.get("labels") or {}).items()))
                ex_text = (f" # {{{ex_pairs}}} {exemplar.get('value', 0)}"
                           f" {exemplar.get('ts', 0)}")
            cum = 0
            for le, n in zip(s.get("boundaries", ()),
                             s.get("buckets", ())):
                cum += n
                le_pairs = pairs + [f'le="{format(float(le), "g")}"']
                attach = ""
                if ex_text and exemplar.get("value", 0) <= float(le):
                    attach, ex_text = ex_text, ""
                lines.append(
                    f"{name}_bucket{{{','.join(le_pairs)}}} {cum}{attach}")
            inf_pairs = pairs + ['le="+Inf"']
            lines.append(
                f"{name}_bucket{{{','.join(inf_pairs)}}} "
                f"{s['count']}{ex_text}")
            lines.append(f"{name}_count{label} {s['count']}")
            lines.append(f"{name}_sum{label} {s['sum']}")
        else:
            lines.append(f"{name}{label} {s['value']}")
    return "\n".join(lines) + "\n"


def create_app(gcs_address: str, session_dir: str):
    from aiohttp import web

    clients = ClientPool()
    gcs = clients.get(gcs_address)
    jobs = JobManager(gcs_address, session_dir)

    def _nodes():
        infos = gcs.call("GetAllNodes", retries=3)
        return [{
            "node_id": i.node_id.hex(), "address": i.address,
            "alive": i.alive, "total_resources": i.total_resources,
            "available_resources": i.available_resources,
            "labels": i.labels,
        } for i in infos.values()]

    async def _call(fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    async def nodes(_req):
        return web.json_response(await _call(_nodes))

    async def actors(_req):
        return web.json_response(
            await _call(lambda: gcs.call("ListActors", retries=3)))

    async def pgs(_req):
        return web.json_response(
            await _call(lambda: gcs.call("ListPlacementGroups",
                                         retries=3)))

    async def objects(_req):
        # Directory joined with per-daemon residency (size / pins /
        # tier / chunk-cache) — the same join `art memory` renders, so
        # the UI and the CLI show one truth.
        def build():
            from ant_ray_tpu._private.state_aggregator import (  # noqa: PLC0415
                list_objects_joined,
            )

            return list_objects_joined(gcs, clients)
        return web.json_response(await _call(build))

    async def tasks(req):
        """Server-side-filtered task state from the bounded GCS table
        (?state=&name=&job_id=&actor_id=&node_id=&limit=&token=)."""
        query = req.query

        def build():
            token = query.get("token")
            return gcs.call("ListTasks", {
                "state": query.get("state"),
                "name": query.get("name"),
                "job_id": query.get("job_id"),
                "actor_id": query.get("actor_id"),
                "node_id": query.get("node_id"),
                "limit": int(query.get("limit", 1000)),
                "token": int(token) if token else None,
            }, retries=3)
        return web.json_response(await _call(build))

    async def tasks_summary(req):
        job_id = req.query.get("job_id")

        def build():
            return gcs.call("SummarizeTasks", {"job_id": job_id},
                            retries=3)
        return web.json_response(await _call(build))

    async def memory(req):
        top_n = int(req.query.get("top", 20))

        def build():
            from ant_ray_tpu._private.state_aggregator import (  # noqa: PLC0415
                build_memory_report,
            )

            return build_memory_report(gcs, clients, top_n=top_n)
        return web.json_response(await _call(build))

    def _ha_view():
        try:
            return gcs.call("GetHaView", {}, timeout=5, retries=1)
        except Exception:  # noqa: BLE001 — pre-HA head
            return None

    async def cluster_status(_req):
        def build():
            infos = gcs.call("GetAllNodes", retries=3)
            total = gcs.call("ClusterResources", retries=3)
            avail = gcs.call("AvailableResources", retries=3)
            return {"nodes_alive": sum(i.alive for i in infos.values()),
                    "nodes_dead": sum(not i.alive
                                      for i in infos.values()),
                    "resources_total": total,
                    "resources_available": avail,
                    "ha": _ha_view()}
        return web.json_response(await _call(build))

    async def ha(_req):
        """Control-plane HA view: leader identity, standby set with
        per-follower replication lag, last failover timestamp."""
        return web.json_response(await _call(_ha_view))

    async def scale(_req):
        """Scale observatory: the head's per-subsystem cost counters
        (GetScaleStats — per-method handle time, scheduler scan width,
        heartbeat ingest, table/ring occupancy, io-loop duty), with
        the handle counters pre-ranked for direct rendering."""
        def build():
            stats = gcs.call("GetScaleStats", retries=3)
            stats["handle_ranked"] = [
                {"method": m, "calls": c,
                 "total_ms": round(ns / 1e6, 2),
                 "us_per_call": round(ns / c / 1e3, 2) if c else None}
                for m, (c, ns) in sorted(
                    stats.get("handle", {}).items(),
                    key=lambda kv: -kv[1][1])]
            return stats
        return web.json_response(await _call(build))

    async def insight(_req):
        def build():
            from ant_ray_tpu.util.insight import build_call_graph  # noqa: PLC0415

            events = gcs.call("InsightGet", {"limit": 10000}, retries=3)
            return {"events": events[-1000:],
                    "graph": build_call_graph(events)}
        return web.json_response(await _call(build))

    async def export_events(req):
        def build():
            return gcs.call("ExportEventsGet", {
                "source_type": req.query.get("source_type"),
                "limit": int(req.query.get("limit", 1000)),
            }, retries=3)
        return web.json_response(await _call(build))

    async def node_logs(req):
        node_id = req.query.get("node_id")

        def build():
            infos = gcs.call("GetAllNodes", retries=3)
            out = []
            for info in infos.values():
                if not info.alive:
                    continue
                if node_id and not info.node_id.hex().startswith(node_id):
                    continue
                files = clients.get(info.address).call(
                    "ListLogs", {}, retries=3)
                out.append({"node_id": info.node_id.hex(),
                            "files": files})
            return out
        return web.json_response(await _call(build))

    async def node_log_read(req):
        filename = req.match_info["filename"]
        node_id = req.query.get("node_id")
        tail = req.query.get("tail")

        def build():
            infos = gcs.call("GetAllNodes", retries=3)
            last_error = f"no alive node matches {node_id!r}"
            for info in infos.values():
                if not info.alive:
                    continue
                if node_id and not info.node_id.hex().startswith(node_id):
                    continue
                reply = clients.get(info.address).call(
                    "ReadLog",
                    {"filename": filename,
                     "tail": int(tail) if tail else None}, retries=3)
                if "error" in reply:
                    # The file lives on exactly one node — keep trying
                    # the other matches before reporting failure.
                    last_error = reply["error"]
                    continue
                return {"node_id": info.node_id.hex(),
                        "data": reply["data"].decode(
                            "utf-8", errors="replace"),
                        "eof": reply["eof"]}
            return {"error": last_error}
        return web.json_response(await _call(build))

    async def timeline(_req):
        def build():
            from ant_ray_tpu.util.timeline import build_chrome_trace  # noqa: PLC0415

            events = gcs.call("TaskEventsGet", {"limit": 50000},
                              retries=3) or []
            steps = gcs.call("StepEventsGet", {"limit": 20000},
                             retries=3) or []
            try:
                spans = gcs.call("SpanEventsGet", {"limit": 50000},
                                 retries=3) or []
            except Exception:  # noqa: BLE001 — pre-upgrade GCS
                spans = []
            try:
                profiles = gcs.call("CpuProfileGet", {"limit": 4000},
                                    retries=3) or []
            except Exception:  # noqa: BLE001 — pre-upgrade GCS
                profiles = []
            return build_chrome_trace(events, step_events=steps,
                                      span_events=spans,
                                      cpu_profile=profiles)
        return web.json_response(await _call(build))

    async def cpuprofile(req):
        """Merged collapsed-stack capture of the whole cluster (or one
        node with ``?node_id=<prefix>``): the CLI `profile` data behind
        an HTTP GET.  ``?since_ts=`` narrows the window."""
        def build():
            from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

            payload: dict = {}
            if req.query.get("node_id"):
                payload["node_id"] = req.query["node_id"]
            if req.query.get("proc"):
                payload["proc"] = req.query["proc"]
            if req.query.get("since_ts"):
                payload["since_ts"] = float(req.query["since_ts"])
            records = gcs.call("CpuProfileGet", payload, retries=3) or []
            merged = cpu_profiler.merge_folded(records)
            return {"records": len(records),
                    "procs": sorted({r.get("proc", "?")
                                     for r in records}),
                    "samples": sum(int(r.get("samples") or 0)
                                   for r in records),
                    "stacks": merged,
                    "collapsed": cpu_profiler.render_folded(merged)}
        return web.json_response(await _call(build))

    async def trace(req):
        """One request's span tree: every hop (ingress → router →
        replica → nested tasks → pulls → lease grants) that published
        under this trace id, folded into a parent/child forest."""
        trace_id = req.match_info["trace_id"]

        def build():
            from ant_ray_tpu.observability.tracing_plane import span_tree  # noqa: PLC0415

            spans = gcs.call("SpanEventsGet", {"trace_id": trace_id},
                             retries=3) or []
            return {"trace_id": trace_id, "span_count": len(spans),
                    "spans": spans, "tree": span_tree(spans)}
        return web.json_response(await _call(build))

    async def flightrecorder(req):
        """Live per-node flight-recorder rings (always on): the node
        daemon's in-memory spans — including force-sampled error spans
        — even when batch publication lags or the GCS ring wrapped.
        ``?node_id=<prefix>`` narrows to one node."""
        node_id = req.query.get("node_id")
        limit = int(req.query.get("limit", 0) or 0)

        def build():
            infos = gcs.call("GetAllNodes", retries=3)
            out = []
            for info in infos.values():
                if not info.alive:
                    continue
                if node_id and not info.node_id.hex().startswith(node_id):
                    continue
                try:
                    reply = clients.get(info.address).call(
                        "GetFlightRecorder", {"limit": limit},
                        timeout=5)
                except Exception:  # noqa: BLE001 — node mid-death
                    continue
                out.append(reply)
            return out
        return web.json_response(await _call(build))

    async def profile(req):
        """On-demand XLA trace capture: route the request to the target
        node's agent, which runs ``jax.profiler.trace`` into the
        session dir and archives it into the log dir (so the existing
        /api/logs routes list and serve it)."""
        try:
            body = await req.json()
        except Exception:  # noqa: BLE001 — empty body = defaults
            body = {}
        node_id = body.get("node_id")
        try:
            duration = float(body.get("duration_s", 2.0))
        except (TypeError, ValueError):
            return web.json_response({"error": "duration_s must be a "
                                               "number"}, status=400)

        def build():
            infos = gcs.call("GetAllNodes", retries=3)
            last_error = f"no alive node matches {node_id!r}"
            for info in infos.values():
                if not info.alive:
                    continue
                if node_id and not info.node_id.hex().startswith(node_id):
                    continue
                agent = clients.get(info.address).call(
                    "GetAgentInfo", {}, timeout=5) or {}
                addr = agent.get("address")
                if not addr or not agent.get("alive"):
                    # With no node pinned, keep looking: another node's
                    # agent may be alive even if this one is down.
                    last_error = ("node has no live agent (start the "
                                  "cluster with ART_ENABLE_NODE_AGENT=1)")
                    if node_id:
                        return {"error": last_error,
                                "node_id": info.node_id.hex()}
                    continue
                reply = dict(clients.get(addr).call(
                    "AgentProfile", {"duration_s": duration},
                    timeout=duration + 90) or {})
                reply["node_id"] = info.node_id.hex()
                return reply
            return {"error": last_error}
        return web.json_response(await _call(build))

    async def index(_req):
        from ant_ray_tpu._private.dashboard_ui import INDEX_HTML  # noqa: PLC0415

        return web.Response(text=INDEX_HTML, content_type="text/html")

    async def metrics(req):
        # Content negotiation: OpenMetrics scrapers (Accept names
        # application/openmetrics-text) get exemplar suffixes and the
        # mandatory EOF marker; classic text-format scrapers get plain
        # 0.0.4 lines (exemplars would fail their whole scrape).
        openmetrics = "application/openmetrics-text" in \
            req.headers.get("Accept", "")

        def build():
            series = gcs.call("MetricsGet", retries=3)
            infos = gcs.call("GetAllNodes", retries=3)
            avail = gcs.call("AvailableResources", retries=3)
            total = gcs.call("ClusterResources", retries=3)
            builtin = [
                {"name": "art_cluster_nodes_alive", "type": "gauge",
                 "tags": {}, "value": sum(
                     i.alive for i in infos.values()),
                 "description": "alive nodes"},
            ]
            # Per-node series, gathered from each daemon (role of the
            # reference's per-node metrics agents,
            # dashboard/agent.py:24 + _private/metrics_agent.py —
            # redesigned: the node daemon exports its own gauges over
            # RPC and the head scrapes, so there is no extra agent
            # process per node).  Scrapes run in PARALLEL: a hung
            # daemon costs one timeout, not one per node, keeping
            # /metrics inside Prometheus's scrape window.
            import concurrent.futures  # noqa: PLC0415

            def scrape(info):
                node_series = clients.get(info.address).call(
                    "GetNodeMetrics", {}, timeout=5)
                short = info.node_id.hex()[:12]
                for entry in node_series:
                    entry.setdefault("tags", {})["node_id"] = short
                return node_series

            alive = [i for i in infos.values() if i.alive]
            with concurrent.futures.ThreadPoolExecutor(
                    max_workers=min(16, max(1, len(alive)))) as pool:
                for fut in [pool.submit(scrape, i) for i in alive]:
                    try:
                        builtin.extend(fut.result())
                    except Exception:  # noqa: BLE001 — node mid-death
                        continue
            # The text format requires one contiguous group per metric
            # family; per-node appends interleave families, so sort
            # (stable: per-node order within a family is kept).
            builtin.sort(key=lambda e: e["name"])
            for res, tot in total.items():
                builtin.append({
                    "name": "art_cluster_resource_total", "type": "gauge",
                    "tags": {"resource": res}, "value": tot,
                    "description": "total cluster resources"})
                builtin.append({
                    "name": "art_cluster_resource_available",
                    "type": "gauge", "tags": {"resource": res},
                    "value": avail.get(res, 0.0),
                    "description": "available cluster resources"})
            text = _prometheus_text(builtin + series,
                                    exemplars=openmetrics)
            return text + "# EOF\n" if openmetrics else text
        return web.Response(
            text=await _call(build),
            content_type=("application/openmetrics-text" if openmetrics
                          else "text/plain"))

    async def submit_job(req):
        body = await req.json()
        if "entrypoint" not in body:
            return web.json_response({"error": "entrypoint required"},
                                     status=400)
        try:
            job_id = await _call(
                lambda: jobs.submit(
                    body["entrypoint"], body.get("runtime_env"),
                    body.get("submission_id"), body.get("metadata")))
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=409)
        return web.json_response({"submission_id": job_id})

    async def list_jobs(_req):
        return web.json_response(await _call(jobs.list))

    async def get_job(req):
        job = await _call(jobs.get, req.match_info["job_id"])
        if job is None:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response(job)

    async def job_logs(req):
        text = await _call(jobs.logs, req.match_info["job_id"])
        return web.json_response({"logs": text})

    async def stop_job(req):
        ok = await _call(jobs.stop, req.match_info["job_id"])
        return web.json_response({"stopped": bool(ok)})

    app = web.Application()
    app.router.add_get("/", index)
    app.router.add_get("/api/nodes", nodes)
    app.router.add_get("/api/actors", actors)
    app.router.add_get("/api/placement_groups", pgs)
    app.router.add_get("/api/objects", objects)
    app.router.add_get("/api/tasks", tasks)
    app.router.add_get("/api/tasks/summary", tasks_summary)
    app.router.add_get("/api/memory", memory)
    app.router.add_get("/api/cluster_status", cluster_status)
    app.router.add_get("/api/ha", ha)
    app.router.add_get("/api/scale", scale)
    app.router.add_get("/api/insight", insight)
    app.router.add_get("/api/export_events", export_events)
    app.router.add_get("/api/timeline", timeline)
    app.router.add_get("/api/cpuprofile", cpuprofile)
    app.router.add_get("/api/trace/{trace_id}", trace)
    app.router.add_get("/api/flightrecorder", flightrecorder)
    app.router.add_get("/api/logs", node_logs)
    app.router.add_get("/api/logs/{filename}", node_log_read)
    app.router.add_get("/metrics", metrics)
    app.router.add_post("/api/profile", profile)
    app.router.add_post("/api/jobs", submit_job)
    app.router.add_get("/api/jobs", list_jobs)
    app.router.add_get("/api/jobs/{job_id}", get_job)
    app.router.add_get("/api/jobs/{job_id}/logs", job_logs)
    app.router.add_post("/api/jobs/{job_id}/stop", stop_job)
    app["job_manager"] = jobs
    return app


def main():  # pragma: no cover — subprocess entry, driven by tests
    import argparse

    from aiohttp import web

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--monitor-pid", type=int, default=0)
    args = parser.parse_args()

    app = create_app(args.gcs_address, args.session_dir)
    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    runner = web.AppRunner(app)
    loop.run_until_complete(runner.setup())
    site = web.TCPSite(runner, "127.0.0.1", args.port)
    loop.run_until_complete(site.start())
    port = site._server.sockets[0].getsockname()[1]
    print(f"DASH_READY http://127.0.0.1:{port}", flush=True)

    async def watch_parent():
        while True:
            await asyncio.sleep(1.0)
            if args.monitor_pid:
                try:
                    os.kill(args.monitor_pid, 0)
                except ProcessLookupError:
                    app["job_manager"].shutdown()
                    loop.stop()
                    return

    loop.create_task(watch_parent())
    try:
        loop.run_forever()
    except KeyboardInterrupt:
        pass
    app["job_manager"].shutdown()


if __name__ == "__main__":
    main()
