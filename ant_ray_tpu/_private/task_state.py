"""GCS-side task state aggregation: the bounded per-(task, attempt)
state table behind the ``ListTasks`` / ``GetTask`` / ``SummarizeTasks``
state API (ref: GcsTaskManager, src/ray/gcs/gcs_task_manager.h:97 —
the reference folds core-worker task events into a bounded, GC'd task
table at ingestion so state queries never replay the raw event ring).

Design constraints, in order:

* **Ingest stays cheap.**  ``apply()`` runs once per event on the GCS
  io loop, inline with ``TaskEventsAdd`` (the comment in gcs.py's
  handler pins why: recording per-event work costs double-digit
  percentages of async task throughput on a small head).  The fold is
  a dict upsert plus a few assignments — no sorting, no allocation
  beyond the record dict, benched by ``task_state_ingest_overhead_ns``.
* **Out-of-order tolerant, forward-only.**  Flush batches from
  different processes interleave arbitrarily: the driver's
  ``submitted`` routinely lands after the worker's ``finished``.  A
  record's state only moves FORWARD through the rank below, terminal
  states are sticky (equal-rank arrivals never overwrite — a late
  ``finished`` flush cannot erase ``FAILED``), and per-state
  timestamps are kept regardless of arrival order so durations stay
  right.
* **Attempts are first-class.**  Records key by ``(task_id, attempt)``
  — a retry's ``started`` must not erase attempt 0's terminal state
  (the client-side fold bug this table replaces).
* **Bounded.**  Per-job cap (``task_table_max_per_job``) with
  evict-finished-first GC (ref: the gcs_task_manager.h:60 policy);
  evictions are counted and surfaced as ``num_tasks_dropped`` so a
  clipped view is never mistaken for a complete one.
"""

from __future__ import annotations

import time

# State ranks: a record only ever moves to a STRICTLY higher rank.
# FINISHED and FAILED share the terminal rank — whichever lands first
# wins, so a late duplicate flush cannot flip a failure to success.
PENDING = "PENDING"
PENDING_EXECUTION = "PENDING_EXECUTION"
RUNNING = "RUNNING"
FINISHED = "FINISHED"
FAILED = "FAILED"

STATE_RANK = {PENDING: 0, PENDING_EXECUTION: 1, RUNNING: 2,
              FINISHED: 3, FAILED: 3}
TERMINAL_STATES = (FINISHED, FAILED)

_EVENT_STATE = {"submitted": PENDING_EXECUTION, "started": RUNNING,
                "finished": FINISHED, "failed": FAILED}
# Wall-clock timestamp slot each event fills (events carry the
# producer's time.time(); cross-process wall clocks are the wire
# convention for these, same as deadline_ts).
_EVENT_TS_KEY = {"submitted": "submitted_ts", "started": "started_ts",
                 "finished": "end_ts", "failed": "end_ts"}


class TaskStateTable:
    """Single-threaded fold of task lifecycle events into per-attempt
    state records (GCS io-loop use: no locks, like the other tables)."""

    def __init__(self, max_per_job: int | None = None):
        # (task_id, attempt) -> record dict.  Insertion-ordered: GC
        # walks oldest-first within its eviction class.
        self._records: dict[tuple[str, int], dict] = {}
        self._by_job: dict[str, int] = {}      # job_id -> live records
        self._dropped_by_job: dict[str, int] = {}
        self._seq = 0              # monotone insert counter (pagination)
        self._max_per_job = max_per_job
        self.num_tasks_dropped = 0   # GC evictions (view clipped)
        self.events_folded = 0

    # ------------------------------------------------------------ ingest

    def _cap(self) -> int:
        if self._max_per_job is not None:
            return self._max_per_job
        from ant_ray_tpu._private.config import global_config  # noqa: PLC0415

        return global_config().task_table_max_per_job

    def apply(self, event: dict) -> None:
        """Fold one lifecycle event (hot path — see module docstring)."""
        state = _EVENT_STATE.get(event.get("event"))
        if state is None:
            return
        self.events_folded += 1
        key = (event["task_id"], int(event.get("attempt") or 0))
        record = self._records.get(key)
        if record is None:
            job_id = event.get("job_id") or ""
            self._seq += 1
            record = {
                "task_id": key[0], "attempt": key[1],
                "name": event.get("name", ""),
                "state": PENDING, "job_id": job_id,
                "actor_id": event.get("actor_id"),
                "parent_task_id": event.get("parent_task_id"),
                "node_id": "", "pid": event.get("pid"),
                "error": None, "trace_id": None,
                "submitted_ts": None, "started_ts": None, "end_ts": None,
                "_seq": self._seq,
            }
            self._records[key] = record
            self._by_job[job_id] = self._by_job.get(job_id, 0) + 1
            if self._by_job[job_id] > self._cap():
                self._gc_job(job_id)
        # Per-state timestamps land regardless of arrival order (a
        # late `submitted` still fills submitted_ts under a FINISHED
        # record, keeping queue-time attribution right).
        ts_key = _EVENT_TS_KEY[event["event"]]
        if record[ts_key] is None:
            record[ts_key] = event.get("ts")
        # Identity fields: fill what this event knows and the record
        # doesn't (the driver's `submitted` carries the parent link,
        # the worker's `started` carries the node).
        if event.get("event") == "started" and event.get("node_id"):
            record["node_id"] = event["node_id"]
        if record["actor_id"] is None and event.get("actor_id"):
            record["actor_id"] = event["actor_id"]
        if record["parent_task_id"] is None and \
                event.get("parent_task_id"):
            record["parent_task_id"] = event["parent_task_id"]
        if not record["job_id"] and event.get("job_id"):
            self._reindex_job(record, event["job_id"])
        if event.get("trace_id"):
            record["trace_id"] = event["trace_id"]
        if event.get("error") and record["error"] is None:
            record["error"] = str(event["error"])[:512]
        # Forward-only state machine: strictly-higher rank moves the
        # state; terminal states are sticky against equal-rank
        # duplicates (FAILED never becomes FINISHED).
        if STATE_RANK[state] > STATE_RANK[record["state"]]:
            record["state"] = state

    def _reindex_job(self, record: dict, job_id: str) -> None:
        """A later event learned the record's job — move the per-job
        accounting off the anonymous bucket."""
        old = record["job_id"]
        self._by_job[old] = self._by_job.get(old, 1) - 1
        if self._by_job.get(old, 0) <= 0:
            self._by_job.pop(old, None)
        record["job_id"] = job_id
        self._by_job[job_id] = self._by_job.get(job_id, 0) + 1
        if self._by_job[job_id] > self._cap():
            self._gc_job(job_id)

    def _gc_job(self, job_id: str) -> None:
        """Evict the job back under its cap: finished attempts first
        (oldest first), then the oldest records of any state — live
        work is the last thing an operator loses sight of."""
        cap = self._cap()
        excess = self._by_job.get(job_id, 0) - cap
        if excess <= 0:
            return
        doomed = []
        for key, record in self._records.items():   # insertion order
            if record["job_id"] != job_id:
                continue
            if record["state"] in TERMINAL_STATES:
                doomed.append(key)
                if len(doomed) >= excess:
                    break
        if len(doomed) < excess:
            have = set(doomed)
            for key, record in self._records.items():
                if record["job_id"] != job_id or key in have:
                    continue
                doomed.append(key)
                if len(doomed) >= excess:
                    break
        for key in doomed:
            del self._records[key]
        self._by_job[job_id] = self._by_job.get(job_id, 0) - len(doomed)
        self._dropped_by_job[job_id] = \
            self._dropped_by_job.get(job_id, 0) + len(doomed)
        self.num_tasks_dropped += len(doomed)

    # ------------------------------------------------------------- reads

    @staticmethod
    def _durations(record: dict) -> dict:
        """Per-stage durations derivable from the filled timestamps
        (None when the bracketing events haven't both arrived)."""
        sub, start, end = (record["submitted_ts"], record["started_ts"],
                           record["end_ts"])
        return {
            "queue_s": (start - sub
                        if sub is not None and start is not None
                        else None),
            "run_s": (end - start
                      if start is not None and end is not None
                      else None),
            "total_s": (end - sub
                        if sub is not None and end is not None
                        else None),
        }

    def _public(self, record: dict) -> dict:
        out = {k: v for k, v in record.items() if k != "_seq"}
        out.update(self._durations(record))
        return out

    @staticmethod
    def _matches(record: dict, filters: dict) -> bool:
        state = filters.get("state")
        if state and record["state"] != state:
            return False
        name = filters.get("name")
        if name and record["name"] != name:
            return False
        job_id = filters.get("job_id")
        if job_id and record["job_id"] != job_id:
            return False
        actor_id = filters.get("actor_id")
        if actor_id and record["actor_id"] != actor_id:
            return False
        node_id = filters.get("node_id")
        if node_id and not record["node_id"].startswith(node_id):
            return False
        return True

    def list(self, filters: dict | None = None, limit: int = 1000,
             token: int | None = None) -> dict:
        """Filtered page of records in insertion order.  ``token`` is
        the opaque continuation cursor from the previous page (the last
        record's insert seq — eviction-safe: GC'd records simply no
        longer appear, never shifting the cursor)."""
        filters = filters or {}
        limit = max(1, int(limit))
        after = int(token or 0)
        out: list[dict] = []
        last_seq = after
        next_token = None
        for record in self._records.values():
            if record["_seq"] <= after or \
                    not self._matches(record, filters):
                continue
            if len(out) >= limit:
                # Another match exists past the page — there IS a next
                # page, resumable after the last record we returned.
                next_token = last_seq
                break
            out.append(self._public(record))
            last_seq = record["_seq"]
        return {"tasks": out, "next_token": next_token,
                "num_tasks_dropped": self.num_tasks_dropped}

    def get(self, task_id: str) -> list[dict]:
        """Every attempt of one task, attempt-ordered."""
        return sorted(
            (self._public(r) for (tid, _a), r in self._records.items()
             if tid == task_id),
            key=lambda r: r["attempt"])

    def summarize(self, filters: dict | None = None) -> dict:
        """Group-by-name rollup: per-state counts plus run-duration
        stats (mean/p50/p99 over attempts with a measured run_s),
        computed here so the client never pulls the table.  ONE rollup
        implementation: delegates to :func:`summarize_public_records`,
        which the HA cross-replica merge path uses too."""
        filters = filters or {}
        reply = summarize_public_records(
            self._public(r) for r in self._records.values()
            if self._matches(r, filters))
        reply["num_tasks_dropped"] = self.num_tasks_dropped
        return reply

    def stats(self) -> dict:
        return {
            "num_records": len(self._records),
            "num_tasks_dropped": self.num_tasks_dropped,
            "events_folded": self.events_folded,
            "dropped_by_job": dict(self._dropped_by_job),
        }


# ------------------------------------------------ cross-replica merge
# (GCS HA: the task-event ring is sharded across replicas by producer —
#  ListTasks/GetTask/SummarizeTasks on any replica fan out local_only
#  queries and merge HERE, with the same forward-only / sticky-terminal
#  rules as apply(), so a task whose events landed on two replicas
#  still reads as one record and FAILED can never un-happen.)

_MERGE_FILL_NONE = ("submitted_ts", "started_ts", "end_ts", "actor_id",
                    "parent_task_id", "trace_id", "error", "pid")
_MERGE_FILL_EMPTY = ("name", "job_id", "node_id")


def merge_public_records(record_lists) -> list[dict]:
    """Merge per-replica public task records (as returned by
    :meth:`TaskStateTable.list`) keyed by ``(task_id, attempt)``.
    State moves by strictly-greater rank (terminal sticky), missing
    timestamps/identity fields fill from whichever replica knows them,
    and durations are recomputed from the merged timestamps."""
    out: dict[tuple, dict] = {}
    for records in record_lists:
        for rec in records or ():
            key = (rec["task_id"], rec["attempt"])
            cur = out.get(key)
            if cur is None:
                out[key] = dict(rec)
                continue
            for field in _MERGE_FILL_NONE:
                if cur.get(field) is None and rec.get(field) is not None:
                    cur[field] = rec[field]
            for field in _MERGE_FILL_EMPTY:
                if not cur.get(field) and rec.get(field):
                    cur[field] = rec[field]
            if STATE_RANK[rec["state"]] > STATE_RANK[cur["state"]]:
                cur["state"] = rec["state"]
    merged = list(out.values())
    for rec in merged:
        rec.update(TaskStateTable._durations(rec))
    # Deterministic order so offset-style continuation over the merged
    # view walks each record exactly once.
    merged.sort(key=lambda r: (r.get("submitted_ts")
                               or r.get("started_ts")
                               or r.get("end_ts") or 0.0,
                               r["task_id"], r["attempt"]))
    return merged


def summarize_public_records(records) -> dict:
    """:meth:`TaskStateTable.summarize` semantics over (merged) public
    records — the rollup a replica computes after the HA fan-in."""
    groups: dict[str, dict] = {}
    durations: dict[str, list[float]] = {}
    for record in records:
        name = record["name"]
        group = groups.get(name)
        if group is None:
            group = groups[name] = {
                "state_counts": {}, "total": 0, "failed": 0}
            durations[name] = []
        group["total"] += 1
        counts = group["state_counts"]
        counts[record["state"]] = counts.get(record["state"], 0) + 1
        if record["state"] == FAILED:
            group["failed"] += 1
        if record.get("run_s") is not None:
            durations[name].append(record["run_s"])
    for name, group in groups.items():
        runs = sorted(durations[name])
        if runs:
            group["run_s"] = {
                "count": len(runs),
                "mean": sum(runs) / len(runs),
                "p50": runs[len(runs) // 2],
                "p99": runs[min(len(runs) - 1,
                                int(0.99 * (len(runs) - 1)))],
            }
        else:
            group["run_s"] = None
    return {"summary": groups,
            "total_tasks": sum(g["total"] for g in groups.values())}


def ingest_overhead_ns(n: int = 20000) -> float:
    """Per-event fold cost (the ``task_state_ingest_overhead_ns``
    microbench body lives with the table it measures): folds ``n``
    synthetic submit/start/finish triples through one table and
    reports ns per EVENT."""
    table = TaskStateTable(max_per_job=n * 4)
    base = time.time()
    events = []
    for i in range(n // 3):
        tid = f"t{i:08x}"
        events.append({"task_id": tid, "name": "bench", "job_id": "j",
                       "event": "submitted", "ts": base, "attempt": 0})
        events.append({"task_id": tid, "name": "bench", "job_id": "j",
                       "event": "started", "ts": base + 0.001,
                       "node_id": "n1", "attempt": 0})
        events.append({"task_id": tid, "name": "bench", "job_id": "j",
                       "event": "finished", "ts": base + 0.002,
                       "attempt": 0})
    t0 = time.perf_counter()
    apply = table.apply
    for event in events:
        apply(event)
    elapsed = time.perf_counter() - t0
    return elapsed / max(1, len(events)) * 1e9
