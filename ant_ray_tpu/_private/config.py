"""Global configuration flag table.

Equivalent in spirit to the reference's RAY_CONFIG macro table
(ref: src/ray/common/ray_config_def.h — 239 flags, env-overridable via
RAY_<name>), redesigned as a typed dataclass: every field is overridable with
an ``ART_<NAME>`` environment variable and with the ``_system_config`` dict
passed to :func:`ant_ray_tpu.init`.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(f"ART_{name.upper()}")
    if raw is None:
        return default
    ty = type(default)
    if ty is bool:
        return raw.lower() in ("1", "true", "yes")
    if ty is int:
        return int(raw)
    if ty is float:
        return float(raw)
    if ty in (dict, list):
        return json.loads(raw)
    return raw


@dataclasses.dataclass
class Config:
    # ---- object store ----
    # Objects smaller than this are returned inline in RPC replies and live in
    # the owner's in-process memory store; larger ones go to the node's shared
    # memory store (ref: max_direct_call_object_size).
    max_inline_object_size: int = 100 * 1024
    # Per-node shared-memory store capacity (bytes). 0 = auto (30% of RAM).
    object_store_memory: int = 0
    # Chunk size for node-to-node object transfer.
    object_transfer_chunk_size: int = 8 * 1024 * 1024
    # Holder-side memo of recently served transfer chunks: a broadcast
    # to N nodes costs one store read per chunk, not N (ref: PushManager
    # chunk dedup, push_manager.h:28).  0 disables.
    transfer_chunk_cache_bytes: int = 64 * 1024 * 1024
    # Cap on a node's in-flight inbound transfer bytes; pulls beyond it
    # queue (ref: pull_manager.h:50 quota).  0 = unlimited.  A single
    # object larger than the quota still pulls (alone).  A striped pull
    # accounts its whole object size ONCE, not per stripe.
    pull_quota_bytes: int = 256 * 1024 * 1024
    # ReadChunk requests kept in flight per holder during a pull, so
    # transfer bandwidth is bounded by the wire, not chunk_size/RTT
    # (ref: PushManager's in-flight chunk window, push_manager.h:28).
    # 1 degenerates to the stop-and-wait protocol.
    object_pull_window: int = 8
    # Objects at least this large with >=2 registered holders pull
    # STRIPED: the chunk range is partitioned across holders and pulled
    # concurrently into the same grant (broadcast fan-in at k x NIC).
    # 0 disables striping.
    object_stripe_min_bytes: int = 16 * 1024 * 1024
    # Testing only: holder-side delay per served transfer chunk, so
    # tests can deterministically kill a holder mid-transfer.
    testing_chunk_serve_delay_s: float = 0.0
    # Testing only (chaos harness): truncate every bulk-channel chunk
    # reply to at most this many payload bytes (0 = off).  The puller
    # sees a short reply, fails the pump, and exercises the stripe
    # failover path deterministically.
    testing_chunk_truncate: int = 0
    # An unsealed arena grant younger than this is presumed live (its
    # producer is still writing); only older grants are reclaimed.
    unsealed_grant_ttl_s: float = 30.0
    # Arena read pins auto-expire after this long if the reader never
    # sends ReadDone (crashed client), so the slot becomes evictable.
    read_pin_ttl_s: float = 120.0
    # Zero-copy get() pins (arrays deserialized as views into the arena)
    # live until the consumer GCs the value; clients renew the lease at
    # TTL/3 (RenewPin heartbeat) while the value is referenced, so this
    # only bounds how long a *crashed* reader can wedge a slot.
    zero_copy_pin_ttl_s: float = 120.0
    # EnsureLocal fails fast after this many seconds with an empty
    # holder list, handing control to lineage reconstruction.
    pull_no_holders_grace_s: float = 2.0
    # Start the dashboard head (REST state API + /metrics + job server)
    # with the cluster.
    include_dashboard: bool = True
    # Emit flow-insight call-graph events (ant-fork util/insight).
    enable_insight: bool = False
    # Stream worker stdout/stderr lines to the driver console via GCS
    # pubsub (ref: log_monitor.py) — `print()` inside a task shows up
    # on the driver as `(worker=.. pid=..) line`.
    log_to_driver: bool = True
    # Spawn a per-node agent process (runtime-env builds, log serving,
    # OS metrics) supervised by the daemon (ref: agent_manager.h + the
    # dashboard/runtime-env agents).  Builds fall back in-process while
    # the agent is down.
    enable_node_agent: bool = True
    # Node-agent interval for publishing per-device HBM gauges
    # (observability/device_stats.py) into the GCS metrics table.
    # 0 disables the publish loop (stats stay available on demand via
    # the AgentDeviceStats RPC).
    device_stats_interval_s: float = 15.0
    # Mirror per-task lifecycle events into the export pipeline (ref:
    # the reference's per-source enable_export_api_write gates).  Off by
    # default: tasks are the one high-volume source and recording each
    # event costs real control-plane throughput.
    export_task_events: bool = False
    # Task lifecycle events (submitted/started/finished) buffered per
    # process and batch-flushed to the GCS — feeds the Chrome-trace
    # timeline and the state API (ref: task_event_buffer.h).
    enable_task_events: bool = True
    # Evicted sealed objects spill to disk (session dir) and restore on
    # access instead of being dropped (ref: LocalObjectManager).
    enable_object_spilling: bool = True
    # Per-node spill budget; past it, evictions drop instead of spill.
    max_spill_bytes: int = 10 * 1024 * 1024 * 1024
    # LRU-evict unpinned objects when the store is this full.
    object_store_high_watermark: float = 0.8

    # ---- data engine ----
    # Max concurrent tasks per streaming-Data stage (map / split / merge).
    data_inflight_tasks: int = 8
    # Per-stage cap on estimated in-flight block bytes: further launches
    # wait once the sum of known in-window block sizes passes it (ref:
    # streaming executor backpressure policies,
    # data/_internal/execution/backpressure_policy/).  0 disables.
    data_inflight_bytes: int = 128 * 1024 * 1024
    # Target output block size: size-aware repartition/shuffle pick
    # their partition count from total bytes / this when the caller
    # gives no explicit block count.
    data_target_block_bytes: int = 32 * 1024 * 1024

    # ---- cgroup v2 isolation (ref: src/ray/common/cgroup2/) ----
    # Place workers in a sibling cgroup under a delegated cgroup2 tree
    # (opt-in; silently skipped when the tree isn't writable).
    enable_cgroups: bool = False
    # The delegated cgroup2 tree root (tests point this at a fake).
    cgroup_root: str = "/sys/fs/cgroup"
    # Collective memory.max for the workers cgroup (bytes; 0 = no cap).
    cgroup_workers_memory_max: int = 0
    # cpu.weight for the workers cgroup (0 = kernel default).
    cgroup_workers_cpu_weight: int = 0

    # ---- scheduling ----
    # Workers pre-started per node at boot (-1 = auto: min(2, num_cpus)).
    num_prestart_workers: int = -1
    # Upper bound on workers a node will fork (0 = num_cpus).
    max_workers_per_node: int = 0
    # Seconds an idle leased worker is kept before release.
    worker_lease_timeout_s: float = 0.5
    # Spill a queued task to another node if it has waited this long locally.
    spillback_timeout_s: float = 0.2
    # How long a task submission keeps following spillback redirects on
    # a busy cluster before giving up (the redirect chain itself is
    # unbounded, matching the reference submitter).
    lease_retry_deadline_s: float = 120.0
    # Lease reuse (ref: NormalTaskSubmitter scheduling-key entries,
    # normal_task_submitter.cc:185 — leased workers are reused for
    # queued tasks of the same scheduling key instead of paying a
    # lease/return RPC pair per task):
    # how long a drained worker lease lingers waiting for the next task
    # of its key before being returned to the node.
    task_lease_linger_s: float = 0.05
    # In-flight PushTask pipeline depth per leased worker (hides the RPC
    # round trip behind execution of the previous task).
    task_push_pipeline_depth: int = 8
    # Max concurrent LeaseWorker requests parked per scheduling key.
    max_pending_lease_requests: int = 8
    # Worker leases requested per LeaseWorker round trip: a burst of N
    # queued tasks asks the daemon for up to this many workers in ONE
    # RPC (payload ``count``); the daemon grants extras only from
    # already-idle capacity (reply ``extra``), and grants the queue
    # drained past are returned immediately.  1 restores the one-lease-
    # per-round-trip protocol (and is what pre-batching daemons serve).
    lease_batch_size: int = 8
    # Pull-before-grant budget for a lease's plasma args (ref:
    # LeaseDependencyManager, lease_dependency_manager.h): the daemon
    # pulls the first queued task's deps node-local before granting,
    # waiting at most this long.  0 disables.
    lease_dep_prefetch_timeout_s: float = 10.0

    # ---- fault tolerance ----
    task_max_retries_default: int = 3
    actor_max_restarts_default: int = 0
    # Node heartbeat period and the number of missed beats before death.
    heartbeat_period_s: float = 0.5
    num_heartbeats_timeout: int = 10
    # Stagger each node's heartbeat phase by a hash of its node id
    # within heartbeat_period_s, so N daemons booted together cannot
    # synchronize into ingest storms at the GCS.
    heartbeat_jitter: bool = True
    # Cap on the exponential backoff a daemon applies between CONSECUTIVE
    # failed heartbeat sends (a flapping GCS link must not busy-spin);
    # kept well under the death timeout so one recovered beat still
    # lands in time.
    heartbeat_backoff_cap_s: float = 2.0
    # A node daemon whose GCS has been unreachable this long exits
    # (fail-stop for orphans; GCS FT restarts return well inside it).
    # 0 disables.
    gcs_dead_exit_s: float = 60.0
    # Remote lease-owner liveness sweep (node_daemon): ping period and
    # the number of consecutive failed pings before a reclaim is even
    # considered.  High-latency deployments raise these; the reclaim
    # additionally corroborates with GCS node liveness — an owner
    # whose node is still heartbeating is never reclaimed over a
    # transient partition between daemon and owner.
    lease_owner_sweep_interval_s: float = 3.0
    lease_owner_ping_strikes: int = 3
    # Hybrid (DEFAULT) scheduling: pack onto feasible nodes until their
    # utilization passes this, then spread (ref:
    # hybrid_scheduling_policy.h spread_threshold).
    hybrid_pack_threshold: float = 0.5
    # Sticky pack-pick cache in the GCS scheduler: reuse the last grant
    # target per plain scheduling shape (revalidated against live state)
    # instead of an O(nodes) feasibility scan per lease — the worst
    # measured cliff in the 500-node scale harness.  Off restores the
    # full-scan-per-lease behaviour (the harness's "before" arm).
    sched_pick_cache: bool = True

    # Node-side virtual-cluster fencing verdicts are cached this long
    # before re-checking with the GCS (ant ref: virtual-cluster GC/TTL
    # flags, ray_config_def.ant.h).
    vc_fence_ttl_s: float = 5.0

    # ---- autoscaler ----
    # How long an infeasible task waits for the autoscaler to provision
    # a node before failing (only applies while an autoscaler heartbeat
    # is live; without one infeasible fails fast).
    infeasible_wait_s: float = 300.0

    # ---- GCS HA (replicated control plane) ----
    # Leader lease TTL: a leader that cannot renew within it is fenced
    # out and a standby takes over — the dominant term in failover time.
    gcs_ha_lease_ttl_s: float = 2.0
    # How often the holder renews (and standbys poll) the lease.
    gcs_ha_renew_period_s: float = 0.4
    # Follower store-sync period: bounds follower-read staleness and
    # the replication lag reported in the HA view.
    gcs_ha_sync_period_s: float = 0.25
    # Client-side failover budget: how long the GCS router keeps
    # re-resolving the leader (capped-backoff probes over the known
    # replica set) after a connection failure before surfacing the
    # error.  Only applies when the client knows >1 replica.
    gcs_failover_timeout_s: float = 15.0
    # Remote-store read fence budget (store_client.RemoteStoreClient):
    # how long a read waits for the ordered write queue to drain before
    # failing with a typed StoreFenceError.  A fence miss must surface,
    # not silently return possibly-stale state — follower reads build
    # their read-your-writes guarantee on this.
    store_fence_timeout_s: float = 10.0

    # ---- rpc ----
    rpc_connect_timeout_s: float = 10.0
    rpc_call_timeout_s: float = 60.0
    # Hot-frame wire protocol (hotframe.py): the zero-pickle PushTask
    # path — struct-packed calls against per-connection header
    # templates, with coalesced batched acks.  Negotiated per
    # connection in the HELLO handshake; disabling it (or talking to a
    # peer that has it disabled / predates it) transparently falls back
    # to the pickled frames, call for call.
    hot_wire_enabled: bool = True
    # Deterministic RPC fault injection: "method:prob,method:prob" (chaos
    # testing — ref: src/ray/rpc/rpc_chaos.h).
    testing_rpc_failure: str = ""
    # Deterministic RPC latency injection: "method:seconds,method:seconds"
    # (chaos harness — slow-replica / slow-network scenarios; the delay
    # is added client-side before the frame is written, so it rides the
    # same per-daemon env channel as testing_rpc_failure).
    testing_rpc_latency_s: str = ""

    # ---- memory monitor (ref: src/ray/common/memory_monitor.h +
    # worker_killing_policy.h) ----
    # Check node memory pressure this often; 0 disables the monitor.
    memory_monitor_interval_s: float = 1.0
    # Above this used fraction, the daemon kills a worker to relieve
    # pressure (retriable task workers first, largest RSS first).
    memory_usage_threshold: float = 0.95
    # Where to read meminfo (tests point this at a fake file).
    meminfo_path: str = "/proc/meminfo"
    # ---- filesystem monitor (ref: src/ray/common/file_system_monitor.h:
    # above the capacity threshold a node stops taking new work so
    # spill/log writes can't wedge the whole node).  0 interval disables.
    fs_monitor_interval_s: float = 5.0
    local_fs_capacity_threshold: float = 0.95

    # ---- accelerators / preemption ----
    # Override detected TPU chip count (testing).
    tpu_chips_override: int = -1
    # Node-daemon poll period for pending TPU maintenance events /
    # preemption notices (accelerators.tpu.maintenance_notice); on a
    # notice the daemon drains itself via the GCS DrainNode RPC.
    # 0 disables the watcher.
    preemption_poll_interval_s: float = 1.0
    # Default drain grace (seconds) announced with a preemption-driven
    # drain when the notice itself carries no deadline — consumers
    # (Train controllers, Serve) must be off the node within it.
    drain_deadline_s: float = 30.0
    # Testing only (chaos harness): path of a file whose EXISTENCE is a
    # preemption notice for this node's daemon — the deterministic
    # stand-in for the TPU maintenance-event metadata API.  First line
    # may carry "<deadline_s> <reason...>".
    testing_preemption_notice: str = ""

    # ---- tracing (observability/tracing_plane.py) ----
    # Head-sampling rate for request traces: the coin is flipped ONCE at
    # each ingress (serve HTTP/gRPC request, handle.call, driver
    # .remote()) and the verdict propagates with the context, Dapper
    # style.  Error/shed spans are force-sampled regardless.  1.0 traces
    # everything (tests/debugging); 0 disables minting sampled traces.
    trace_sample_rate: float = 0.01
    # Per-process flight-recorder ring size (spans).  Force-sampled
    # error spans keep a separate ring of size/4 so healthy traffic
    # wrapping the main ring never evicts failure evidence.
    flight_recorder_size: int = 4096
    # Sampled spans batch-published to the GCS span ring once this many
    # are pending (age-flushed at 1s regardless).
    trace_publish_batch: int = 128

    # ---- continuous CPU profiling (observability/cpu_profiler.py) ----
    # Sampling rate of the always-on wall-clock profiler that every
    # process class (driver, daemons, workers, GCS replicas, agents)
    # runs.  67 Hz is the classic off-by-one-from-round prime that
    # avoids lockstep with 10ms/100ms periodic work; 0 disables the
    # whole profiling plane (sampler, publication, wire-counter
    # rollups).  Env channel: ART_CPU_PROFILE_HZ.
    cpu_profile_hz: float = 67.0
    # How often each process publishes its folded-stack delta (and its
    # wire-accounting counter deltas) to the GCS CpuProfileAdd ring.
    cpu_profile_publish_period_s: float = 2.5
    # Bound on DISTINCT folded stacks aggregated per process; once full,
    # new stacks collapse into a single "(overflow)" bucket so a
    # pathological stack churn can't grow memory.
    cpu_profile_max_stacks: int = 800

    # ---- cluster state observatory (_private/task_state.py) ----
    # Per-job cap on the GCS task-state table (ref: GcsTaskManager's
    # MAX_NUM_TASK_EVENTS_PER_JOB GC policy, gcs_task_manager.h:60):
    # once a job exceeds this many (task, attempt) records, finished
    # attempts are evicted first (oldest first), then the oldest
    # non-terminal records; evictions surface as num_tasks_dropped in
    # ListTasks/SummarizeTasks/GetTask stats so operators know the
    # view is clipped.
    task_table_max_per_job: int = 10000
    # Record the creation callsite (file:line outside the framework) of
    # plasma objects at put() time, surfaced by `art memory` /
    # /api/memory.  Off by default: the stack walk costs ~microseconds
    # per put and the strings cost directory memory.
    record_object_callsite: bool = False

    # ---- lockcheck (_lint/lockcheck.py) ----
    # Opt-in runtime lock-order detector for the daemon planes: the
    # make_lock/make_rlock factories return instrumented wrappers that
    # record the per-process lock-acquisition graph, report cycles
    # (lock-order inversion = potential deadlock) and budget-exceeding
    # holds across known-blocking calls through the flight recorder.
    # Off (default) the factories return plain threading locks — zero
    # overhead.  Env channel: ART_LOCKCHECK=1 (inherited by spawned
    # daemons, so one env var arms a whole local cluster).
    lockcheck: bool = False
    # A lock held longer than this across a note_blocking() call (sync
    # RPC, socket I/O, subprocess) is reported as a long-hold.
    lockcheck_hold_budget_s: float = 0.25

    # ---- logging ----
    log_level: str = "INFO"

    def apply_env_overrides(self) -> "Config":
        for f in dataclasses.fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))
        return self

    def apply_dict(self, overrides: dict | None) -> "Config":
        if not overrides:
            return self
        for key, value in overrides.items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown config flag: {key}")
            setattr(self, key, value)
        return self


_global_config: Config | None = None


def global_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config().apply_env_overrides()
    return _global_config


def set_global_config(config: Config) -> None:
    global _global_config
    _global_config = config
