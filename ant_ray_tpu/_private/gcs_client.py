"""Leader-aware GCS client router (the HA half of ClientPool).

``ClientPool.get()`` returns one of these for a comma-joined replica
spec ("host:p1,host:p2,host:p3"), presenting the exact RpcClient call
surface — so every existing ``pool.get(gcs_address)`` call site
(daemons, workers, serve/train controllers, dashboard, CLI) gains HA
routing without changing:

* **mutations** go to the presumed leader; a typed
  :class:`~ant_ray_tpu._private.protocol.NotLeaderError` redirect
  re-targets them, and a dead leader triggers the re-resolve path —
  ``GetHaView`` probes over the known replica set with capped backoff,
  bounded by the ``gcs_failover_timeout_s`` budget — instead of
  surfacing "no route";
* **follower reads** (wire_schema.GCS_FOLLOWER_READS) round-robin over
  live standbys so read load scales with them;
* **ring writes** (wire_schema.GCS_RING_WRITES — task/step/span event
  ingestion) shard by a per-process key over ALL live replicas;
  ``ring_epoch`` increments whenever the live set changes, which is the
  signal producers (task_events.TaskEventBuffer) use to replay their
  terminal-event tails so a killed replica's ring slice cannot lose a
  terminal task state.

With a single known address (no HA deployed) every call degrades to
exactly the plain-RpcClient behavior: same target, same errors, no
failover spinning.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.protocol import (
    IoThread,
    NotLeaderError,
    RpcConnectionError,
    _spawn,
)
from ant_ray_tpu._private.wire_schema import (
    GCS_FOLLOWER_READS,
    GCS_RING_WRITES,
)

logger = logging.getLogger(__name__)

# How long a resolved HA view is trusted before an opportunistic
# background refresh (keeps the follower set and ring shard current
# without a per-call RPC).
_VIEW_TTL_S = 2.0
_MAX_REDIRECTS = 3


class GcsRouter:
    """Routes one logical GCS endpoint over a replica set.  Thread-safe
    the same way RpcClient is: all await-side state lives on the io
    loop; the routing tables are whole-object swaps (GIL-atomic reads),
    never in-place mutation."""

    def __init__(self, spec: str, pool):
        self.address = spec              # identity: the original spec
        self._pool = pool
        seeds = [a.strip() for a in spec.split(",") if a.strip()]
        if not seeds:
            raise ValueError(f"empty GCS replica spec: {spec!r}")
        self._known: list[str] = list(dict.fromkeys(seeds))
        # Single-replica fast path: with one known replica there is no
        # leader to resolve, no followers to round-robin, and no ring
        # to shard — bind the plain client once and skip the routing
        # layer entirely (the per-call shard/epoch arithmetic is
        # measurable on the ring-write ingest path).
        self._solo: str | None = (self._known[0]
                                  if len(self._known) == 1 else None)
        self._solo_client = None
        self._leader: str = self._known[0]
        self._followers: list[str] = []
        self._live: list[str] = list(self._known)
        self._rr = 0
        # Ring-shard key: stable per process, so one producer's event
        # stream lands on one replica (the "sharded by key" contract —
        # merged back at query time by the replicas themselves).
        self._shard_key = os.getpid()
        self.ring_epoch = 0
        self._view_ts = 0.0
        self._refreshing = False
        self._io = IoThread.get()
        self._closed = False

    # ------------------------------------------------------------ routing

    def _route(self, method: str) -> str:
        if method in GCS_RING_WRITES:
            live = self._live or [self._leader]
            return live[self._shard_key % len(live)]
        if method in GCS_FOLLOWER_READS:
            followers = self._followers
            if followers:
                self._rr += 1
                return followers[self._rr % len(followers)]
        return self._leader

    def _set_leader(self, addr: str) -> None:
        if addr and addr != self._leader:
            self._leader = addr
            self._followers = [a for a in self._followers if a != addr]

    def _mark_dead(self, addr: str) -> None:
        if addr in self._live and len(self._live) > 1:
            self._live = [a for a in self._live if a != addr]
            self.ring_epoch += 1
        self._followers = [a for a in self._followers if a != addr]

    def _absorb_view(self, view) -> None:
        if not isinstance(view, dict):
            return
        leader = view.get("leader") or ""
        replicas = view.get("replicas") or []
        live = [r["address"] for r in replicas if r.get("address")]
        if not live and view.get("address"):
            live = [view["address"]]
        if set(live) != set(self._live):
            self.ring_epoch += 1
        self._live = live
        self._known = list(dict.fromkeys([*self._known, *live]))
        self._followers = [r["address"] for r in replicas
                           if r.get("address")
                           and r.get("role") != "leader"
                           and r["address"] != leader]
        if leader:
            self._set_leader(leader)
        self._view_ts = time.monotonic()

    async def _resolve(self) -> bool:
        """One probe round over every known replica: adopt the first
        view whose leader answers for itself.  Returns True when a
        live, self-reporting leader is known.  A standby's view can
        lag (it names the leader whose store ad it last synced — which
        may be the replica that just died), so a leader learned second-
        hand is verified by probing it directly."""
        candidates = list(dict.fromkeys(
            [self._leader, *self._live, *self._known]))
        probed: set[str] = set()
        for addr in candidates:
            if addr in probed:
                continue
            probed.add(addr)
            try:
                view = await self._pool.get(addr).call_async(
                    "GetHaView", {}, timeout=2)
            except Exception:  # noqa: BLE001 — dead/slow replica: next
                continue
            self._absorb_view(view)
            if view.get("role") == "leader":
                return True          # straight from the horse's mouth
            leader = view.get("leader")
            if leader and leader not in probed:
                probed.add(leader)
                try:
                    confirm = await self._pool.get(leader).call_async(
                        "GetHaView", {}, timeout=2)
                except Exception:  # noqa: BLE001 — stale second-hand ad
                    continue
                self._absorb_view(confirm)
                if confirm.get("role") == "leader":
                    return True
        return False

    def _maybe_refresh(self) -> None:
        """Opportunistic background view refresh (fire-and-forget):
        keeps follower/ring routing current on a healthy cluster so
        failovers and standby additions are noticed between errors."""
        if len(self._known) <= 1:
            return                      # no HA deployed: nothing to learn
        if self._refreshing or \
                time.monotonic() - self._view_ts < _VIEW_TTL_S:
            return
        self._refreshing = True

        async def _bg():
            try:
                await self._resolve()
            finally:
                self._refreshing = False

        _spawn(_bg())

    # ------------------------------------------------------------- calls

    def _solo_bound(self):
        """The bound plain client of a single-replica spec (re-fetched
        from the pool only if it was invalidated under us)."""
        client = self._solo_client
        if client is None or client._closed:
            client = self._solo_client = self._pool.get(self._solo)
        return client

    async def call_async(self, method: str, payload=None,
                         timeout: float | None = None):
        if self._solo is not None:
            # Plain-RpcClient semantics: same target, same errors, no
            # failover spinning, no routing arithmetic.
            return await self._solo_bound().call_async(
                method, payload, timeout)
        self._maybe_refresh()
        target = self._route(method)
        deadline = None
        delay = 0.05
        redirects = 0
        while True:
            try:
                return await self._pool.get(target).call_async(
                    method, payload, timeout)
            except NotLeaderError as e:
                redirects += 1
                if e.leader_addr and e.leader_addr != target and \
                        redirects <= _MAX_REDIRECTS:
                    # Typed redirect: retarget without burning backoff.
                    self._set_leader(e.leader_addr)
                    target = self._route(method)
                    continue
                # Election in progress (no leader advertised yet, or a
                # redirect loop): fall through to resolve + backoff.
            except RpcConnectionError:
                self._mark_dead(target)
                if len(self._known) <= 1:
                    raise            # single replica: plain semantics
            if deadline is None:
                deadline = time.monotonic() + \
                    global_config().gcs_failover_timeout_s
            if time.monotonic() >= deadline:
                err = RpcConnectionError(
                    f"no reachable GCS leader among {self._known} "
                    "within the failover budget "
                    f"({global_config().gcs_failover_timeout_s:.0f}s)")
                # Tell the sync retry wrapper the budget is already
                # spent: a caller's ``retries=3`` must not multiply a
                # 15s failover budget into a minute-long hang against
                # a fully-dead replica set.
                err.failover_budget_exhausted = True
                raise err
            await self._resolve()
            target = self._route(method)
            await asyncio.sleep(
                min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2, 2.0)

    async def oneway_async(self, method: str, payload=None) -> None:
        if self._solo is not None:
            await self._solo_bound().oneway_async(method, payload)
            return
        self._maybe_refresh()
        target = self._route(method)
        try:
            await self._pool.get(target).oneway_async(method, payload)
            return
        except RpcConnectionError:
            self._mark_dead(target)
            if len(self._known) <= 1:
                raise
        # One re-shard retry: oneways are best-effort, but a dead ring
        # replica should cost one epoch bump, not a silent drop.
        await self._resolve()
        retry = self._route(method)
        if retry == target:
            raise RpcConnectionError(
                f"no live GCS replica for oneway {method}")
        await self._pool.get(retry).oneway_async(method, payload)

    async def oneway_many(self, items) -> None:
        """Batched-oneway surface (RpcClient.oneway_many contract, used
        by the coalesced publish drain).  Solo specs ship the whole
        batch in one write; replicated specs route per item — each
        method may shard differently."""
        if self._solo is not None:
            await self._solo_bound().oneway_many(items)
            return
        for method, payload in items:
            await self.oneway_async(method, payload)

    def call(self, method: str, payload=None,
             timeout: float | None = None, retries: int = 0):
        """Blocking call from any non-io thread (RpcClient.call
        contract, including the retry semantics callers rely on)."""
        from ant_ray_tpu._lint.lockcheck import note_blocking  # noqa: PLC0415

        note_blocking(f"GcsRouter.call:{method}")
        attempt = 0
        while True:
            try:
                return self._io.run_coro(
                    self.call_async(method, payload, timeout))
            except RpcConnectionError as e:
                attempt += 1
                if attempt > retries or \
                        getattr(e, "failover_budget_exhausted", False):
                    raise
                time.sleep(min(0.1 * 2 ** attempt, 2.0))

    # ---------------------------------------------------------- lifecycle

    def close(self) -> None:
        # The per-replica RpcClients belong to the pool and are closed
        # by it; the router itself holds no sockets.
        self._closed = True

    # ------------------------------------------------------------ surface

    def ha_view(self, timeout: float = 5.0) -> dict:
        """Convenience for status surfaces: the current HA view from
        whichever replica answers first."""
        return self.call("GetHaView", {}, timeout=timeout, retries=1)
