"""In-process memory store for owned objects (ref:
src/ray/core_worker/store_provider/memory_store/).

Entries hold the terminal state of every object this process owns:
    ("pending", None)          — task not finished / value not produced yet
    ("inline", payload)        — small object, serialized payload held here
    ("error", payload)         — serialized exception (raised at get)
    ("plasma", size)           — large object, lives in the shm object plane

Thread-safe producers; consumers wait either synchronously (app threads) or
asynchronously (io-loop handlers serving borrower GetObject RPCs).
"""

from __future__ import annotations

import asyncio

from ant_ray_tpu._lint.lockcheck import make_rlock
from ant_ray_tpu._private.ids import ObjectID


class MemoryStore:
    def __init__(self, loop: asyncio.AbstractEventLoop):
        self._loop = loop
        self._entries: dict[ObjectID, tuple] = {}
        self._async_waiters: dict[ObjectID, list[asyncio.Future]] = {}
        # Any-change subscription (io-loop side): WaitObjects long-polls
        # park here and are woken by EVERY terminal put, so one parked
        # reply covers a whole batch of refs without per-ref futures.
        self._change_waiters: list[asyncio.Future] = []
        # REENTRANT: any allocation inside the critical sections can
        # trigger GC, which may run ObjectRef.__del__ -> _refcount_event
        # -> is_owned() on the SAME thread — a plain Lock self-deadlocks
        # the io loop there (observed via create_future inside
        # wait_async; the same class of bug as the reference-counter
        # RLock in core.py).
        self._lock = make_rlock("memory_store")

    def mark_pending(self, object_id: ObjectID) -> None:
        with self._lock:
            self._entries.setdefault(object_id, ("pending", None))

    def put(self, object_id: ObjectID, kind: str, value) -> None:
        assert kind in ("inline", "error", "plasma"), kind
        with self._lock:
            self._entries[object_id] = (kind, value)
            waiters = self._async_waiters.pop(object_id, [])
            change_waiters, self._change_waiters = \
                self._change_waiters, []
        for fut in waiters:
            self._loop.call_soon_threadsafe(self._resolve, fut, (kind, value))
        for fut in change_waiters:
            self._loop.call_soon_threadsafe(self._resolve, fut, True)

    @staticmethod
    def _resolve(fut: asyncio.Future, entry: tuple) -> None:
        if not fut.done():
            fut.set_result(entry)

    def get_entry(self, object_id: ObjectID) -> tuple | None:
        with self._lock:
            return self._entries.get(object_id)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry is not None and entry[0] != "pending"

    def is_owned(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._entries

    async def wait_async(self, object_id: ObjectID,
                         timeout: float | None = None) -> tuple:
        """Await a terminal entry (must run on the io loop)."""
        # Allocate the future OUTSIDE the lock: create_future can GC
        # (see the RLock note above) and fewer allocation points inside
        # the critical section means fewer reentrant excursions.
        fut = self._loop.create_future()
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry[0] != "pending":
                return entry
            self._async_waiters.setdefault(object_id, []).append(fut)
        try:
            if timeout is None:
                return await fut
            return await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, asyncio.CancelledError):
            # Abandoned waiter (timed-out wait_for / cancelled wait()
            # task): remove it NOW — a long-pending object polled in a
            # loop would otherwise accumulate one dead future per call
            # until its eventual put().
            with self._lock:
                waiters = self._async_waiters.get(object_id)
                if waiters is not None:
                    try:
                        waiters.remove(fut)
                    except ValueError:
                        pass
                    if not waiters:
                        del self._async_waiters[object_id]
            raise

    def change_future(self) -> asyncio.Future:
        """Register a future resolved on the NEXT terminal put.  Long
        pollers register BEFORE snapshotting entries, so a cross-thread
        put between snapshot and park can never be missed."""
        fut = self._loop.create_future()
        with self._lock:
            self._change_waiters.append(fut)
        return fut

    def discard_change_future(self, fut: asyncio.Future) -> None:
        with self._lock:
            try:
                self._change_waiters.remove(fut)
            except ValueError:
                pass

    async def wait_change(self, timeout: float,
                          fut: asyncio.Future | None = None) -> bool:
        """Park until ANY object turns terminal (or timeout); returns
        whether a change fired.  Must run on the io loop."""
        if fut is None:
            fut = self.change_future()
        try:
            await asyncio.wait_for(fut, timeout)
            return True
        except asyncio.TimeoutError:
            self.discard_change_future(fut)
            return False

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._entries.pop(object_id, None)
            waiters = self._async_waiters.pop(object_id, [])
        for fut in waiters:
            self._loop.call_soon_threadsafe(
                lambda f=fut: f.cancel() if not f.done() else None)
