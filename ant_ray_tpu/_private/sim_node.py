"""Stub node for the scale observatory (benchmarks/scale_harness.py).

A :class:`StubNode` is a lightweight IN-PROCESS stand-in for a node
daemon that speaks the real wire protocol end-to-end against a real
GCS: it registers with a real ``NodeInfo``, runs the versioned
heartbeat/resource-sync loop (same semantics as
``node_daemon._heartbeat_loop``: view rides the beat only when its
version moved past the acked one, ``unknown_node`` re-registers,
``resync`` resends the full view, phase jitter + failure backoff), it
serves ``LeaseWorker``/``ReturnWorker`` on its own :class:`RpcServer`
so scheduler-granted lease traffic lands on it over TCP, it flushes
task-event batches shaped like ``task_events._expand``'s wire dicts,
and it can park a ``SubPoll`` long-poll subscription.  What it does
NOT have: worker processes, an object-store arena, an agent, spill
queues, or task execution — a lease grant only moves the availability
view (which is exactly what the control plane sees), so ONE driver
process hosts hundreds of stubs on the shared io loop and the GCS
experiences an N-node cluster's full control-plane load.

Fidelity envelope (what a measurement here does/doesn't mean):

* REAL: wire frames + per-connection state at the GCS (each stub owns
  its ClientPool → its own TCP connection and HA router), heartbeat
  ingest cost, versioned view sync, scheduler scan cost per lease,
  pubsub fan-out, task-event fold cost, node-death sweeps, failover
  re-resolve behaviour.
* SIMULATED: lease grants decrement the stub's availability and grant
  a fake worker id — no worker fork, no PushTask, no object traffic.
  A lease that does not fit replies ``infeasible`` instead of queueing
  (the real daemon parks it in a spillback queue).
* ABSENT: data plane, agents, cgroup/memory monitors, log streaming.
"""

from __future__ import annotations

import asyncio
import logging
import time

from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.ids import NodeID, TaskID, WorkerID
from ant_ray_tpu._private.protocol import (
    ClientPool,
    IoThread,
    RpcServer,
)
from ant_ray_tpu._private.specs import NodeInfo

logger = logging.getLogger(__name__)


class StubNode:
    """One simulated node: real control-plane protocol, no workers."""

    def __init__(self, gcs_address: str, *, num_cpus: float = 4.0,
                 resources: dict | None = None,
                 labels: dict | None = None):
        self.node_id = NodeID.from_random()
        self._gcs_address = gcs_address
        total = dict(resources or {})
        total.setdefault("CPU", float(num_cpus))
        self._total = total
        self._available = dict(total)
        # Granted worker_id -> resources held (released by ReturnWorker).
        self._leases: dict[WorkerID, dict] = {}
        # Returned worker ids, recycled on the next grant — the real
        # daemon's idle worker pool, minus the processes.  Keeps
        # ReturnWorker idempotent (known-but-idle -> True) and bounds
        # id growth to the concurrent-lease high-water mark.
        self._idle_workers: list[WorkerID] = []
        self._labels = dict(labels or {})
        self._server = RpcServer()
        # Own pool per stub: a real daemon owns its TCP connection (and
        # its leader-aware router under HA) — sharing one pool across
        # stubs would collapse N connections into one and understate
        # per-connection cost at the GCS.
        self._pool = ClientPool()
        self._gcs = None
        self._info: NodeInfo | None = None
        self._stopping = False
        self._tasks: list = []
        self._view_version = 0
        self._sync_wakeup: asyncio.Event | None = None
        self.address = ""
        self.stats = {"beats": 0, "views_sent": 0, "failures": 0,
                      "reregisters": 0, "leases_granted": 0,
                      "leases_infeasible": 0, "leases_returned": 0,
                      "events_flushed": 0, "pub_events_seen": 0,
                      "sub_errors": 0}

    # ------------------------------------------------------- lifecycle

    def start(self, timeout: float = 30.0) -> str:
        """Boot the RPC server, register with the GCS, and start the
        heartbeat loop.  Returns this stub's wire address."""
        self._server.routes({
            "LeaseWorker": self._lease_worker,
            "ReturnWorker": self._return_worker,
            "GetNodeInfo": self._get_node_info,
            "Ping": self._ping,
        })
        self.address = self._server.start()
        self._gcs = self._pool.get(self._gcs_address)
        if hasattr(self._gcs, "_shard_key"):
            # Ring-write sharding (TaskEventsAdd & co) is keyed per
            # producer PROCESS in a real cluster; hundreds of stubs
            # sharing this driver's pid would collapse every ring
            # write onto one replica.  Re-key per stub.
            self._gcs._shard_key = int(self.node_id.hex()[:8], 16)
        self._info = NodeInfo(
            node_id=self.node_id, address=self.address,
            total_resources=dict(self._total),
            available_resources=dict(self._available),
            labels=self._labels)
        io = IoThread.get()
        io.run_coro(self._register(), timeout=timeout)
        self._spawn_loop(self._heartbeat_loop())
        return self.address

    def _spawn_loop(self, coro) -> None:
        task = asyncio.run_coroutine_threadsafe(coro,
                                                IoThread.get().loop)
        self._tasks.append(task)

    def start_task_event_loop(self, rate_hz: float,
                              batch: int = 16) -> None:
        """Open-loop task-event load: ``rate_hz`` events/s flushed in
        TaskEventsAdd batches of ``batch`` (submitted/started/finished
        triples over synthetic task ids)."""
        self._spawn_loop(self._task_event_loop(rate_hz, batch))

    def subscribe(self, channels=("node",)) -> None:
        """Park a long-poll SubPoll subscription on the GCS (each stub
        holds one poller, like a daemon's watch loops)."""
        self._spawn_loop(self._sub_loop(tuple(channels)))

    def stop(self) -> None:
        self._stopping = True
        event = self._sync_wakeup
        if event is not None:
            IoThread.get().call_soon(event.set)
        for task in self._tasks:
            task.cancel()
        self._tasks.clear()
        self._server.stop()
        self._pool.close_all()

    # ------------------------------------------------- GCS-facing side

    async def _register(self) -> None:
        self._info.available_resources = dict(self._available)
        await self._gcs.call_async("RegisterNode", self._info,
                                   timeout=20)

    async def _heartbeat_loop(self) -> None:
        """``node_daemon._heartbeat_loop``'s protocol, compacted: the
        view rides the beat only while unacked, phase-jittered start,
        capped backoff on consecutive failures.  No fail-stop exit —
        stubs share the driver process, and the harness kills the GCS
        on purpose."""
        cfg = global_config()
        period = cfg.heartbeat_period_s
        self._sync_wakeup = asyncio.Event()
        if cfg.heartbeat_jitter and period > 0:
            phase = (int(self.node_id.hex()[:8], 16) % 997) / 997.0
            await asyncio.sleep(phase * period)
        acked = -1
        consecutive_failures = 0
        while not self._stopping:
            payload: dict = {"node_id": self.node_id}
            version = self._view_version
            if version > acked:
                payload["view"] = {
                    "available_resources": dict(self._available),
                    "disk_full": False,
                    "draining": False,
                    "version": version,
                }
            try:
                reply = await self._gcs.call_async("Heartbeat", payload,
                                                   timeout=10)
                if reply.get("unknown_node"):
                    self.stats["reregisters"] += 1
                    await self._register()
                    acked = -1
                else:
                    if "synced" in reply:
                        acked = max(acked, reply["synced"])
                    if "resync" in reply.get("commands", ()):
                        acked = -1
                self.stats["beats"] += 1
                if "view" in payload:
                    self.stats["views_sent"] += 1
                consecutive_failures = 0
            except Exception:  # noqa: BLE001 — head restarting/failing over
                self.stats["failures"] += 1
                consecutive_failures += 1
            wait = period
            if consecutive_failures > 1:
                wait = max(period, min(
                    period * (2 ** (consecutive_failures - 1)),
                    cfg.heartbeat_backoff_cap_s))
            self._sync_wakeup.clear()
            try:
                await asyncio.wait_for(self._sync_wakeup.wait(), wait)
            except asyncio.TimeoutError:
                pass

    async def _task_event_loop(self, rate_hz: float,
                               batch: int) -> None:
        # Flush cadence: a full batch per flush when the rate allows,
        # capped at 1 s so low per-stub rates (an aggregate rate spread
        # over hundreds of stubs) still flush within a short
        # measurement window.
        period = min(batch / max(rate_hz, 0.001), 1.0)
        triples = max(1, int(round(rate_hz * period / 3)))
        node_hex = self.node_id.hex()[:12]
        while not self._stopping:
            await asyncio.sleep(period)
            events = []
            now = time.time()
            for _ in range(triples):
                task_id = TaskID.from_random().hex()
                for event in ("submitted", "started", "finished"):
                    # The wire dict task_events._expand builds — the
                    # GCS folds these through the same state table a
                    # real worker's flush feeds.
                    events.append({
                        "task_id": task_id, "name": "stub_task",
                        "event": event, "ts": now, "pid": 0,
                        "node_id": node_hex, "worker": self.address,
                        "actor_id": None, "parent_task_id": None,
                        "attempt": 0, "job_id": None,
                    })
            try:
                await self._gcs.call_async("TaskEventsAdd",
                                           {"events": events},
                                           timeout=10)
                self.stats["events_flushed"] += len(events)
            except Exception:  # noqa: BLE001 — ride out a failover
                await asyncio.sleep(0.5)

    async def _sub_loop(self, channels: tuple) -> None:
        cursor = -1
        while not self._stopping:
            try:
                reply = await self._gcs.call_async(
                    "SubPoll", {"channels": list(channels),
                                "cursor": cursor, "timeout": 5.0},
                    timeout=30)
                cursor = reply["cursor"]
                self.stats["pub_events_seen"] += len(reply["events"])
            except Exception:  # noqa: BLE001 — ride out a failover
                self.stats["sub_errors"] += 1
                await asyncio.sleep(0.5)

    # ---------------------------------------------- node-facing server

    def _bump_view(self) -> None:
        self._view_version += 1
        if self._sync_wakeup is not None:
            self._sync_wakeup.set()  # sub-period view propagation

    async def _lease_worker(self, payload):
        """Grant shape parity with ``node_daemon._lease_worker_impl``:
        ``{"granted": worker_addr, "worker_id": id}`` (+ ``extra``
        grants from idle capacity for batched leases), or
        ``infeasible`` when the request does not fit — the stub does
        not model the real daemon's spillback queue."""
        resources = payload.get("resources") or {}
        count = max(1, int(payload.get("count", 1)))

        def fits() -> bool:
            return all(self._available.get(k, 0.0) >= v
                       for k, v in resources.items())

        def grant() -> WorkerID:
            worker_id = (self._idle_workers.pop()
                         if self._idle_workers
                         else WorkerID.from_random())
            for key, value in resources.items():
                self._available[key] = self._available.get(key, 0.0) \
                    - value
            self._leases[worker_id] = dict(resources)
            self.stats["leases_granted"] += 1
            return worker_id

        if not fits():
            self.stats["leases_infeasible"] += 1
            return {"infeasible": True,
                    "reason": "stub node saturated"}
        primary = grant()
        extra = []
        while len(extra) < count - 1 and fits():
            extra.append({"granted": self.address,
                          "worker_id": grant()})
        self._bump_view()
        reply = {"granted": self.address, "worker_id": primary}
        if extra:
            reply["extra"] = extra
        return reply

    async def _return_worker(self, payload):
        worker_id = payload.get("worker_id")
        held = self._leases.pop(worker_id, None)
        if held is None:
            # Daemon parity: returning an already-idle worker is a
            # no-op True; only a never-seen worker id is False.
            return worker_id in self._idle_workers
        for key, value in held.items():
            self._available[key] = self._available.get(key, 0.0) + value
        self._idle_workers.append(worker_id)
        self.stats["leases_returned"] += 1
        self._bump_view()
        return True

    async def _get_node_info(self, _payload):
        self._info.available_resources = dict(self._available)
        return self._info

    async def _ping(self, _payload):
        return True


__all__ = ["StubNode"]
