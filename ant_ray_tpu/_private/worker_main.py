"""Worker process entry point.

Role of the reference's worker main + task execution path (ref:
python/ray/_private/workers/default_worker.py + src/ray/core_worker/
task_execution/task_receiver.h:44): registers with the node daemon, serves
PushTask / InstantiateActor on the in-process core service, and executes
tasks on an executor thread (per-actor ordered; thread pool when the actor
declares max_concurrency > 1; coroutine methods run on a persistent asyncio
loop).
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import os
import queue
import threading
import time
import traceback
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from ant_ray_tpu import exceptions
from ant_ray_tpu._private import serialization, task_events
from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.core import ClusterRuntime
from ant_ray_tpu._private.ids import JobID, NodeID, ObjectID, WorkerID
from ant_ray_tpu._private.protocol import IoThread
from ant_ray_tpu._private.specs import (
    ACTOR_ALIVE,
    ACTOR_DEAD,
    ActorSpec,
    PromotedArgs,
    TaskSpec,
)
from ant_ray_tpu._private.worker import CLUSTER_MODE, global_worker
from ant_ray_tpu.object_ref import ObjectRef
from ant_ray_tpu.observability import tracing_plane

logger = logging.getLogger(__name__)


class TaskExecutor:
    """Executes tasks for this worker; one main executor thread (actor order
    preserved), optional thread pool for max_concurrency > 1 actors."""

    # Cancelled-id memory bound: ids for tasks that already ran (or
    # never arrive) must not accumulate forever.
    _CANCEL_CAP = 4096

    def __init__(self, runtime: ClusterRuntime):
        self.runtime = runtime
        # SimpleQueue: C-implemented, ~5x cheaper per put/get than
        # queue.Queue — this hop is on every task execution.
        self.queue: "queue.SimpleQueue[tuple]" = queue.SimpleQueue()
        # Task ids cancelled via art.cancel before execution started
        # (CancelTask RPC); checked at both dequeue points so a task
        # parked in the pool's backlog is dropped, not run.
        self._cancelled: "dict[bytes, bool]" = {}
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._cancel_lock = make_lock("worker.cancelled_ids")
        self.actor_instance = None
        self.actor_spec: ActorSpec | None = None
        self._async_loop: asyncio.AbstractEventLoop | None = None
        # Named bounded executor pools (ref: ConcurrencyGroupManager,
        # src/ray/core_worker/task_execution/concurrency_group_manager.h):
        # "" is the default pool, sized by max_concurrency; each declared
        # concurrency group gets its own pool so one group saturating
        # never starves another.
        self._group_pools: dict[str, "ThreadPoolExecutor"] = {}
        self._io = IoThread.get()
        # Coalesced reply channel: executor threads append completed
        # replies here and schedule ONE io-loop drain for the whole
        # burst (the _post_submit idiom) instead of one
        # call_soon_threadsafe per call — the drain resolves every
        # future in the same loop tick, which is what lets the server's
        # hot-ack batch ship a burst of replies as one frame.
        self._reply_inbox: "deque[tuple]" = deque()
        self._reply_scheduled = False
        self._main = threading.Thread(target=self._run_loop, daemon=True,
                                      name="art-executor")
        self._main.start()

    def submit(self, spec, reply_fut: asyncio.Future):
        self.queue.put((spec, reply_fut))

    def _reply(self, fut: asyncio.Future, value):
        self._post_reply(fut, value, False)

    def _reply_exc(self, fut: asyncio.Future, exc: Exception):
        self._post_reply(fut, exc, True)

    def _post_reply(self, fut: asyncio.Future, value, is_exc: bool):
        # Flag-coalesced wakeup: while the io loop has not yet run a
        # scheduled drain, further completions just append — a burst
        # whose replies land while the loop is busy resolves in ONE
        # tick, which is what lets the server's hot-ack batch ship
        # them as one frame.  The flag is cleared before draining, so
        # an append racing the drain at worst costs a redundant
        # (harmless) wakeup, never a lost reply.  Deliberately NOT
        # gated on the task queue: holding a reply while a later task
        # executes can deadlock callers whose blocked call (e.g. a
        # coordination barrier) is what the deferred reply would have
        # unblocked.
        self._reply_inbox.append((fut, value, is_exc))
        if not self._reply_scheduled:
            self._reply_scheduled = True
            self._io.loop.call_soon_threadsafe(self._drain_replies)

    def _drain_replies(self):
        self._reply_scheduled = False
        inbox = self._reply_inbox
        while inbox:
            fut, value, is_exc = inbox.popleft()
            if fut.done():
                continue
            if is_exc:
                fut.set_exception(value)
            else:
                fut.set_result(value)

    def _run_loop(self):
        while True:
            spec, fut = self.queue.get()
            if spec is None:
                return
            aspec = self.actor_spec
            group = getattr(spec, "concurrency_group", "") or ""
            # Declaring ANY concurrency group makes the actor threaded
            # (ref semantics: grouped actors give up per-call ordering),
            # so a long default-group call can never starve the groups.
            threaded = aspec is not None and (
                aspec.max_concurrency > 1 or aspec.concurrency_groups)
            if group and not threaded:
                # Same loud failure _pool_for gives grouped actors: a
                # group name on an ungrouped actor is a caller bug, not
                # something to silently run inline.
                self._reply_exc(fut, exceptions.ArtError(
                    f"concurrency group {group!r} requested but this "
                    "actor declares no concurrency_groups"))
            elif threaded:
                try:
                    self._pool_for(group).submit(
                        self._execute_safely, spec, fut)
                except Exception as e:  # noqa: BLE001 — bad group etc.
                    self._reply_exc(fut, exceptions.ArtError(repr(e)))
            else:
                self._execute_safely(spec, fut)

    def _pool_for(self, group: str) -> "ThreadPoolExecutor":
        pool = self._group_pools.get(group)
        if pool is None:
            aspec = self.actor_spec
            if group:
                limit = (aspec.concurrency_groups or {}).get(group)
                if limit is None:
                    # Loud failure, not a silent 1-wide pool: an
                    # undeclared group (e.g. via .options()) is a caller
                    # bug the creation-time check can't see.
                    raise exceptions.ArtError(
                        f"concurrency group {group!r} is not declared on "
                        f"this actor (declared: "
                        f"{sorted(aspec.concurrency_groups or ())})")
            else:
                limit = aspec.max_concurrency
            pool = ThreadPoolExecutor(
                max_workers=max(1, int(limit or 1)),
                thread_name_prefix=f"art-cg-{group or 'default'}")
            self._group_pools[group] = pool
        return pool

    def cancel(self, task_id) -> None:
        """Mark a task cancelled; it is dropped if not yet executing.
        Running tasks are unaffected (cooperative model)."""
        with self._cancel_lock:
            self._cancelled[task_id._bytes] = True
            while len(self._cancelled) > self._CANCEL_CAP:
                self._cancelled.pop(next(iter(self._cancelled)))

    def _take_cancelled(self, spec: TaskSpec) -> bool:
        with self._cancel_lock:
            return self._cancelled.pop(spec.task_id._bytes, False)

    def _execute_safely(self, spec: TaskSpec, fut: asyncio.Future):
        if self._take_cancelled(spec):
            self._reply(fut, self._error_returns(
                spec, exceptions.TaskCancelledError(
                    spec.task_id, "cancelled before execution")))
            return
        # Propagated trace: the spec carries a sampled context minted at
        # the ingress — set it for the duration of execution so nested
        # submits / gets / pulls from user code land in the same trace,
        # and record the server-side execution span (stages: queue =
        # arrival → executor pickup, execute = user code).
        wire = spec.trace_ctx
        trace_token = exec_ctx = None
        t_wall = t0 = 0.0
        if wire is not None:
            exec_ctx = tracing_plane.TraceContext.from_wire(wire).child()
            trace_token = tracing_plane.set_current(exec_ctx)
            t_wall = time.time()
            t0 = time.perf_counter()
        try:
            result = self._execute(spec)
            if exec_ctx is not None:
                try:
                    self._record_exec_span(spec, exec_ctx, wire, t_wall,
                                           t0, result)
                except Exception:  # noqa: BLE001 — never lose the reply
                    logger.exception("exec span recording failed")
            self._reply(fut, result)
        except SystemExit:
            self._reply(fut, self._error_returns(
                spec, exceptions.ActorDiedError(
                    spec.actor_id, "actor exited via exit_actor()")))
            _report_actor_state(self.runtime, self.actor_spec, ACTOR_DEAD,
                                reason="exit_actor()")
            os._exit(0)
        except Exception as e:  # noqa: BLE001 — internal failure
            logger.exception("internal executor failure")
            self._reply_exc(fut, exceptions.ArtError(repr(e)))
        finally:
            if trace_token is not None:
                tracing_plane.reset(trace_token)

    def _record_exec_span(self, spec: TaskSpec, exec_ctx, wire,
                          t_wall: float, t0: float, result: dict) -> None:
        now = time.perf_counter()
        queue_s = max(0.0, t0 - getattr(spec, "_t_arrival", t0))
        exec_s = now - t0
        err = False
        for kind, data in result.get("returns") or ():
            if kind == "error" or (kind == "stream_end"
                                   and data[1] is not None):
                err = True
                break
        tracing_plane.record_span(
            exec_ctx, f"run:{spec.function_name}",
            ts=t_wall - queue_s, dur_s=queue_s + exec_s,
            stages={"queue": queue_s, "execute": exec_s},
            attrs={"task_id": spec.task_id.hex(),
                   "attempt": spec.attempt,
                   **({"actor_id": spec.actor_id.hex()}
                      if spec.actor_id else {})},
            error=err, span_id=exec_ctx.span_id, parent_id=wire[1],
            service="worker")
        tracing_plane.record_rpc(
            "PushTask", {"queue": queue_s, "execute": exec_s},
            exec_ctx.trace_id)

    # ---- execution

    def _execute(self, spec: TaskSpec) -> dict:
        # Adopt the submitting job's identity: nested submits from this
        # task must carry the job's id (virtual-cluster fencing and
        # task-id lineage key off it).  Skipped when unchanged — id
        # construction is measurable at 10k tasks/s.
        if self.runtime.job_id._bytes != spec.task_id._bytes[:4]:
            self.runtime.job_id = spec.task_id.job_id()
        try:
            args, kwargs = self._load_args(spec)
        except exceptions.ArtError as e:
            # A dependency failed: propagate the *original* error through
            # this task's returns (error lineage, ref: RayTaskError chains).
            return self._error_returns(spec, e)
        insight = None
        if global_config().enable_insight:
            from ant_ray_tpu.util import insight  # noqa: PLC0415

            insight.record_call_begin(spec.function_name,
                                      spec.task_id.hex())
            started = time.monotonic()
        events = None
        if global_config().enable_task_events:
            events = task_events
            events.record(
                spec.task_id.hex(), spec.function_name, "started",
                actor_id=spec.actor_id.hex() if spec.actor_id else None,
                attempt=spec.attempt)
            # Nested submissions from this task record it as parent.
            _task_token = events.current_task.set(spec.task_id.hex())
        try:
            if spec.actor_id is not None:
                if self.actor_instance is None:
                    raise exceptions.ActorDiedError(
                        spec.actor_id, "actor instance not initialized")
                if spec.method_name == "__art_exec_loop__":
                    # Compiled-DAG execution loop: occupies this actor
                    # until the driver tears the channels down
                    # (ref: compiled_dag_node.py actor exec loops).
                    from ant_ray_tpu.dag.compiled import exec_loop  # noqa: PLC0415

                    result = exec_loop(self.actor_instance, *args,
                                       **kwargs)
                elif spec.method_name == "__art_collective__":
                    # Collective DAG node: the op runs against the
                    # group this actor created with
                    # init_collective_group (ref: collective_node.py).
                    from ant_ray_tpu.dag.collective import execute_op  # noqa: PLC0415

                    result = execute_op(*args, **kwargs)
                else:
                    method = getattr(self.actor_instance,
                                     spec.method_name)
                    result = method(*args, **kwargs)
            else:
                fn = self.runtime.fetch_code(spec.function_id)
                result = fn(*args, **kwargs)
            # inspect, not asyncio: on Python < 3.12 asyncio.iscoroutine
            # also matches PLAIN GENERATORS (legacy generator-based
            # coroutine support), which would feed a streaming task's
            # generator to the event loop ("Task got bad yield").
            if inspect.iscoroutine(result):
                result = self._run_coroutine(result)
        except SystemExit:
            raise
        except Exception as e:  # noqa: BLE001 — app error → error returns
            err_cls = (exceptions.ActorError if spec.actor_id is not None
                       else exceptions.TaskError)
            err = err_cls.from_exception(spec.function_name, e)
            if insight is not None:
                insight.record_call_end(
                    spec.function_name, spec.task_id.hex(),
                    time.monotonic() - started, error=True)
            if events is not None:
                events.current_task.reset(_task_token)
                events.record(spec.task_id.hex(), spec.function_name,
                              "failed", attempt=spec.attempt,
                              error=repr(e))
            return self._error_returns(spec, err)
        if spec.num_returns == -1:  # streaming generator task
            # The stream is consumed HERE — events record after it
            # drains (and with the contextvar still set, so tasks the
            # generator body spawns keep their parent linkage).
            out = self._stream_returns(spec, result)
            _count, stream_err = out["returns"][0][1]
            if insight is not None:
                insight.record_call_end(
                    spec.function_name, spec.task_id.hex(),
                    time.monotonic() - started,
                    error=stream_err is not None)
            if events is not None:
                events.current_task.reset(_task_token)
                events.record(spec.task_id.hex(), spec.function_name,
                              "failed" if stream_err is not None
                              else "finished", attempt=spec.attempt,
                              error=(repr(stream_err)
                                     if stream_err is not None
                                     else None))
            return out
        if insight is not None:
            insight.record_call_end(spec.function_name,
                                    spec.task_id.hex(),
                                    time.monotonic() - started)
        if events is not None:
            events.current_task.reset(_task_token)
            events.record(spec.task_id.hex(), spec.function_name,
                          "finished", attempt=spec.attempt)
        values = [result] if spec.num_returns == 1 else list(result)
        if len(values) != spec.num_returns:
            err = exceptions.TaskError(
                spec.function_name, None,
                f"expected {spec.num_returns} return values, "
                f"got {len(values)}")
            return self._error_returns(spec, err)
        return {"returns": [self._package(spec, i, v)
                            for i, v in enumerate(values)]}

    def _run_coroutine(self, coro):
        """Async actor methods run on a persistent loop (so the actor can
        hold loop-bound state across calls)."""
        if self._async_loop is None:
            self._async_loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._async_loop.run_forever,
                                 daemon=True, name="art-actor-async")
            t.start()
        return asyncio.run_coroutine_threadsafe(
            coro, self._async_loop).result()

    def _load_args(self, spec: TaskSpec):
        ser = serialization.SerializedObject.from_payload(spec.args_payload)
        obj = serialization.deserialize(ser)
        if isinstance(obj, PromotedArgs):
            # Large args were promoted to plasma by the submitter; the
            # fetch registers this worker as a borrower of nested refs.
            args, kwargs = self.runtime.get([obj.ref], timeout=None)[0]
        else:
            args, kwargs = obj
        args = [self._maybe_fetch(a) for a in args]
        kwargs = {k: self._maybe_fetch(v) for k, v in kwargs.items()}
        return args, kwargs

    def _maybe_fetch(self, value):
        if isinstance(value, ObjectRef):
            return self.runtime.get([value], timeout=None)[0]
        return value

    def _stream_returns(self, spec: TaskSpec, result) -> dict:
        """Drive a streaming task: each yielded item is shipped to the
        owner the moment it exists (ordered oneways on one connection),
        so the consumer reads item 0 while the task still runs (ref:
        streaming generator path, task_manager.h:67).  The final reply
        carries the end-of-stream marker (count + optional error)."""
        count = 0
        error_payload = None
        owner = self.runtime._clients.get(spec.owner_address)
        try:
            for item in result:
                kind, data = self._package(spec, count, item)
                fut = asyncio.run_coroutine_threadsafe(
                    owner.oneway_async("StreamItem", {
                        "task_id": spec.task_id,
                        "index": count,
                        "kind": kind,
                        "data": data,
                    }), self._io.loop)
                fut.result(timeout=60)
                count += 1
        except Exception as e:  # noqa: BLE001 — mid-stream failure
            err_cls = (exceptions.ActorError if spec.actor_id is not None
                       else exceptions.TaskError)
            err = err_cls.from_exception(spec.function_name, e)
            error_payload = serialization.serialize_error(err).to_payload()
        return {"returns": [("stream_end", (count, error_payload))]}

    def _package(self, spec: TaskSpec, index: int, value):
        oid = ObjectID.for_task_return(spec.task_id, index)
        ser = serialization.serialize(value)
        nbytes = ser.payload_nbytes()
        if nbytes <= global_config().max_inline_object_size:
            return ("inline", ser.to_payload())
        self.runtime._write_plasma(oid, ser)  # serializes into the arena
        return ("plasma", nbytes)

    def _error_returns(self, spec: TaskSpec, err: Exception) -> dict:
        payload = serialization.serialize_error(err).to_payload()
        if spec.num_returns == -1:
            # Streaming task failed before (or instead of) producing a
            # generator: the owner expects exactly one end-of-stream
            # marker, never `[...] * -1 == []`.
            return {"returns": [("stream_end", (0, payload))]}
        return {"returns": [("error", payload)] * spec.num_returns}

def _report_actor_state(runtime: ClusterRuntime, spec: ActorSpec | None,
                        state: str, address: str = "", reason: str = ""):
    if spec is None:
        return
    try:
        runtime._gcs.call("ActorStateUpdate", {
            "actor_id": spec.actor_id,
            "state": state,
            "address": address,
            "node_id": NodeID.from_hex(os.environ["ART_NODE_ID"]),
            "reason": reason,
        }, timeout=10, retries=3)
    except Exception:  # noqa: BLE001
        logger.exception("failed to report actor state")


def main():  # pragma: no cover — exercised via subprocess in tests
    logging.basicConfig(
        level=global_config().log_level,
        format="[worker %(levelname)s %(asctime)s] %(message)s")
    # `kill -USR1 <worker pid>` dumps all thread stacks to the worker's
    # stderr log (the reference's `ray stack` equivalent for debugging
    # a wedged worker).
    import faulthandler  # noqa: PLC0415
    import signal  # noqa: PLC0415

    try:
        faulthandler.register(signal.SIGUSR1, all_threads=True)
    except (AttributeError, ValueError):  # non-posix / no signal here
        pass

    _pin = os.environ.get("ART_JAX_PLATFORM")
    if _pin and (os.environ.get("PALLAS_AXON_POOL_IPS")
                 or os.environ.get("JAX_PLATFORMS") != _pin):
        # Apply the platform pin at the jax.config level BEFORE any user
        # code's raw `import jax` triggers backend resolution: in envs
        # with an eagerly-initializing TPU plugin (e.g. a down tunnel),
        # JAX_PLATFORMS alone doesn't prevent a minutes-long stall on
        # the first op.  The ~1.5s eager import is skipped only when the
        # env-var pin already covers raw imports (JAX_PLATFORMS set to
        # the same platform — raw `import jax` honors it) AND the axon
        # site plugin can't have eagerly registered (trigger stashed by
        # the control-plane env) — then jax loads lazily at first use.
        from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

        import_jax()

    node_address = os.environ["ART_NODE_ADDRESS"]
    gcs_address = os.environ["ART_GCS_ADDRESS"]
    store_dir = os.environ["ART_STORE_DIR"]
    worker_id = WorkerID.from_hex(os.environ["ART_WORKER_ID"])

    runtime = ClusterRuntime(
        role="worker",
        job_id=JobID.from_random(),  # replaced per-task by spec job ids
        gcs_address=gcs_address,
        node_address=node_address,
        store_dir=store_dir,
        worker_id=worker_id,
    )
    global_worker.runtime = runtime
    global_worker.mode = CLUSTER_MODE

    # Continuous CPU profiling: workers use the module singleton with
    # the default runtime-oneway publisher (global_worker is bound now).
    from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

    cpu_profiler.start("worker")

    executor = TaskExecutor(runtime)
    io = IoThread.get()

    def handle_push_task(spec: TaskSpec):
        # Sync fast-route handler: returns the reply future directly, so
        # the server writes the reply from a callback with no Task
        # object per call (see RpcServer.fast_route).
        if spec.trace_ctx is not None:
            spec._t_arrival = time.perf_counter()  # queue-stage anchor
        fut = io.loop.create_future()
        executor.submit(spec, fut)  # sync enqueue preserves arrival order
        return fut

    async def handle_instantiate(spec: ActorSpec):
        executor.actor_spec = spec
        if spec.job_id is not None:
            runtime.job_id = spec.job_id  # actor belongs to its job
        fut = asyncio.get_running_loop().create_future()

        def _do_instantiate():
            try:
                cls = runtime.fetch_code(spec.class_id)
                ser = serialization.SerializedObject.from_payload(
                    spec.args_payload)
                obj = serialization.deserialize(ser)
                if isinstance(obj, PromotedArgs):
                    args, kwargs = runtime.get([obj.ref], timeout=None)[0]
                else:
                    args, kwargs = obj
                args = [executor._maybe_fetch(a) for a in args]
                kwargs = {k: executor._maybe_fetch(v)
                          for k, v in kwargs.items()}
                executor.actor_instance = cls(*args, **kwargs)
                _report_actor_state(runtime, spec, ACTOR_ALIVE,
                                    address=runtime.address)
                io.loop.call_soon_threadsafe(fut.set_result, True)
            except Exception as e:  # noqa: BLE001
                tb = traceback.format_exc()
                logger.error("actor init failed: %s", tb)
                _report_actor_state(
                    runtime, spec, ACTOR_DEAD,
                    reason=f"creation task failed: {e!r}")
                io.loop.call_soon_threadsafe(fut.set_result, False)
                threading.Timer(0.2, lambda: os._exit(1)).start()

        threading.Thread(target=_do_instantiate, daemon=True).start()
        return await fut

    async def handle_ping(_payload):
        return "pong"

    async def handle_cancel(payload):
        executor.cancel(payload["task_id"])
        return True

    runtime.server.routes({
        "InstantiateActor": handle_instantiate,
        "Ping": handle_ping,
        "CancelTask": handle_cancel,
    })
    runtime.server.fast_route("PushTask", handle_push_task)

    runtime._node.call("RegisterWorker", {
        "worker_id": worker_id,
        "address": runtime.address,
        "pid": os.getpid(),
    }, retries=5)
    logger.info("worker %s serving at %s", worker_id.hex()[:8],
                runtime.address)

    # Die with the node daemon (a real node failure takes its workers;
    # the simulated one via Cluster.remove_node must behave the same).
    failures = 0
    while True:
        try:
            runtime._node.call("GetNodeInfo", timeout=5)
            failures = 0
        except Exception:  # noqa: BLE001
            failures += 1
            if failures >= 3:
                logger.warning("node daemon unreachable; worker exiting")
                os._exit(1)
        threading.Event().wait(2.0)


if __name__ == "__main__":
    main()
