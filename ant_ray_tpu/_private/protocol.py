"""Asyncio RPC substrate for the control plane.

Role-equivalent to the reference's gRPC wrappers
(ref: src/ray/rpc/grpc_server.h, retryable_grpc_client.h, rpc_chaos.h) with a
lighter transport: length-prefixed pickle frames over TCP, one shared
background IO thread per process (the analogue of the instrumented asio
io_context, ref: src/ray/common/asio/).  The public surface — ``RpcServer``
with async method handlers, ``RpcClient.call`` with retries and deadline, and
deterministic chaos fault injection — is transport-agnostic so it can be
re-hosted on gRPC without touching callers.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import random
import socket
import threading
import time
from typing import Any, Awaitable, Callable

from ant_ray_tpu._private.config import global_config

logger = logging.getLogger(__name__)


def _trace_current():
    """Sampled trace context active in this task, or None.  Lazy-bound:
    the tracing plane imports config (not protocol), so binding at
    first use avoids ordering surprises during package init."""
    global _trace_current
    from ant_ray_tpu.observability.tracing_plane import (  # noqa: PLC0415
        current_sampled,
    )

    _trace_current = current_sampled
    return current_sampled()

_REQ, _REP, _ERR, _ONEWAY, _HELLO, _GOODBYE = 0, 1, 2, 3, 4, 5

# Wire protocol version (ref: protobuf schema versioning — the pickled
# tuple frames are a fixed contract per version; mixed-version nodes
# fail fast at connect with a clear error instead of corrupting state
# mid-RPC).  Bump on any change to frame shapes or payload contracts;
# see wire_schema.py for the per-method payload registry.
PROTOCOL_VERSION = 1

_HEADER = 8  # u64 big-endian frame length

# Top header bit marks a RAW frame: the body is
# ``u32 meta_len | pickle((kind, msg_id, method, None)) | payload bytes``
# and the payload is handed to the caller as a memoryview over the read
# buffer instead of travelling through pickle.  Raw frames are only ever
# sent in REPLY to a method that opts in (ReadChunkRaw), so the change
# is additive within PROTOCOL_VERSION — peers that never ask never see
# one.
_RAW_FLAG = 1 << 63


class RawReply:
    """Handler-return wrapper: reply with ``data`` as a raw out-of-band
    frame (no pickle copy of the payload).  ``data`` may be bytes or a
    memoryview; it is consumed synchronously by the transport write, so
    views into shared memory are safe as long as the handler returns on
    the io loop without an intervening await (fast routes).

    ``release`` (optional) is invoked exactly once after the transport
    consumed the payload (or the reply was dropped) — handlers use it
    to unpin shared-memory windows they served from."""

    __slots__ = ("data", "release")

    def __init__(self, data, release=None):
        self.data = data
        self.release = release

    def done(self) -> None:
        release, self.release = self.release, None
        if release is not None:
            try:
                release()
            except Exception:  # noqa: BLE001 — reply path must not die
                logger.exception("RawReply release hook failed")

# Transport write-buffer level above which senders await drain (flow
# control); below it, frames are written inline with no await.  Shared by
# client sends and server replies.
_DRAIN_THRESHOLD = 1 << 20


class RpcError(Exception):
    pass


class RpcConnectionError(RpcError):
    pass


class RpcTimeoutError(RpcError):
    pass


class NotLeaderError(RpcError):
    """A GCS mutation reached a replica that is not (or no longer) the
    leader.  Carries the leader's address when the replica knows it, so
    the client-side router (gcs_client.GcsRouter) can redirect instead
    of surfacing "no route".  Raised server-side by the HA mutation
    guard; travels the wire pickled like any handler exception."""

    def __init__(self, leader_addr: str = ""):
        super().__init__(
            "not the GCS leader"
            + (f" (leader at {leader_addr})" if leader_addr
               else " (no leader elected yet)"))
        self.leader_addr = leader_addr

    def __reduce__(self):
        # Default exception pickling replays ``args`` (the formatted
        # message) into __init__, which would masquerade as an address.
        return (NotLeaderError, (self.leader_addr,))


class _ChaosInjector:
    """Deterministic RPC fault injection (ref: src/ray/rpc/rpc_chaos.h:24).

    Config string: ``"method:prob,method2:prob"``; seeded RNG so failures are
    reproducible across runs with the same seed.  A ``seed:<n>`` entry in the
    spec overrides the ``seed`` argument — the channel the chaos harness
    (util/chaos.py) uses to carry its schedule seed through ``_system_config``
    into every daemon's injector.
    """

    def __init__(self, spec: str, seed: int = 0, latency_spec: str = ""):
        self._probs: dict[str, float] = {}
        for part in filter(None, (spec or "").split(",")):
            method, prob = part.split(":")
            if method == "seed":
                seed = int(float(prob))
                continue
            self._probs[method] = float(prob)
        self._rng = random.Random(seed)
        # Per-method injected latency (testing_rpc_latency_s): applied
        # client-side before the request frame is written — the
        # deterministic stand-in for a slow replica / congested link.
        self._delays: dict[str, float] = {}
        for part in filter(None, (latency_spec or "").split(",")):
            method, secs = part.split(":")
            if method == "seed":
                continue
            self._delays[method] = float(secs)

    def should_fail(self, method: str) -> bool:
        prob = self._probs.get(method, 0.0)
        return prob > 0 and self._rng.random() < prob

    def delay_for(self, method: str) -> float:
        return self._delays.get(method, 0.0) if self._delays else 0.0


# ------------------------------------------------------------------- io loop

class IoThread:
    """One background asyncio loop per process; all servers/clients share it."""

    _instance: "IoThread | None" = None
    _lock = threading.Lock()

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name="art-io", daemon=True
        )
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    @classmethod
    def get(cls) -> "IoThread":
        with cls._lock:
            if cls._instance is None or not cls._instance._thread.is_alive():
                cls._instance = cls()
            return cls._instance

    def run_coro(self, coro: Awaitable, timeout: float | None = None) -> Any:
        """Run a coroutine on the io loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, fn: Callable, *args) -> None:
        self.loop.call_soon_threadsafe(fn, *args)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.loop.call_soon_threadsafe(inst.loop.stop)


def _release_raw_result(fut: "asyncio.Future") -> None:
    try:
        result = fut.result()
    except Exception:  # noqa: BLE001 — handler error, nothing to free
        return
    if isinstance(result, RawReply):
        result.done()


# asyncio's loop keeps only weak refs to tasks; hold strong refs here so
# fire-and-forget dispatch/read-loop tasks are never GC'd mid-flight.
_background_tasks: set = set()


def _spawn(coro) -> None:
    task = asyncio.ensure_future(coro)
    _background_tasks.add(task)
    task.add_done_callback(_background_tasks.discard)


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    header = await reader.readexactly(_HEADER)
    length = int.from_bytes(header, "big")
    if length & _RAW_FLAG:
        data = await reader.readexactly(length & ~_RAW_FLAG)
        meta_len = int.from_bytes(data[:4], "big")
        kind, msg_id, method, _ = pickle.loads(data[4:4 + meta_len])
        # Zero-copy hand-off: a view over the (immutable) read buffer.
        return kind, msg_id, method, memoryview(data)[4 + meta_len:]
    data = await reader.readexactly(length)
    return pickle.loads(data)


def _encode_frame(msg: Any) -> bytes:
    data = pickle.dumps(msg, protocol=5)
    return len(data).to_bytes(_HEADER, "big") + data


def _encode_raw_head(kind: int, msg_id: int, method: str,
                     payload_len: int) -> bytes:
    """Header + meta for a raw frame; the payload bytes are written
    separately by the caller (so an arena view never round-trips
    through pickle)."""
    meta = pickle.dumps((kind, msg_id, method, None), protocol=5)
    total = 4 + len(meta) + payload_len
    return ((total | _RAW_FLAG).to_bytes(_HEADER, "big")
            + len(meta).to_bytes(4, "big") + meta)


# -------------------------------------------------------------------- server

class RpcServer:
    """Async RPC server. Handlers: ``async def h(payload) -> reply``.

    Register with :meth:`route`; a handler raising propagates the exception to
    the caller (pickled, re-raised client-side as its original type).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._routes: dict[str, Callable[[Any], Awaitable[Any]]] = {}
        # Sync handlers returning a value or a Future: dispatched without
        # creating a coroutine/Task per request — the hot-path shape for
        # task execution (handler enqueues to an executor and returns its
        # reply future).
        self._fast_routes: dict[str, Callable[[Any], Any]] = {}
        self._server: asyncio.AbstractServer | None = None
        self._io = IoThread.get()
        self.address: str = ""

    def route(self, method: str, handler: Callable[[Any], Awaitable[Any]]):
        self._routes[method] = handler

    def routes(self, handlers: dict[str, Callable]):
        self._routes.update(handlers)

    def fast_route(self, method: str, handler: Callable[[Any], Any]):
        """Register a SYNC handler (may return an asyncio.Future)."""
        self._fast_routes[method] = handler

    def start(self) -> str:
        self._io.run_coro(self._start())
        return self.address

    async def _start(self):
        self._server = await asyncio.start_server(
            self._handle_conn, self._host, self._port
        )
        port = self._server.sockets[0].getsockname()[1]
        self.address = f"{self._host}:{port}"

    async def _handle_conn(self, reader, writer):
        # One write lock per connection: replies are written by concurrently
        # dispatched handler tasks, and StreamWriter.drain() is not safe to
        # call from two coroutines at once when flow control pauses the
        # transport (FlowControlMixin._drain_helper asserts).
        write_lock = asyncio.Lock()
        try:
            while True:
                try:
                    kind, msg_id, method, payload = await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                if kind == _HELLO:
                    peer = (payload or {}).get("proto")
                    if peer != PROTOCOL_VERSION:
                        # Version fence: reply GOODBYE (so the client
                        # fails every call with a clear upgrade message)
                        # and drop the connection.
                        self._write_reply(
                            writer, write_lock,
                            (_GOODBYE, msg_id, method,
                             {"proto": PROTOCOL_VERSION,
                              "reason": f"peer wire protocol v{peer} is "
                                        f"not v{PROTOCOL_VERSION}"}))
                        return
                    continue
                fast = self._fast_routes.get(method)
                if fast is not None:
                    self._dispatch_fast(writer, write_lock, kind, msg_id,
                                        method, payload, fast)
                    continue
                _spawn(
                    self._dispatch(
                        writer, write_lock, kind, msg_id, method, payload)
                )
        finally:
            writer.close()

    def _dispatch_fast(self, writer, write_lock, kind, msg_id, method,
                       payload, handler):
        """Task-free dispatch for sync handlers: the reply is written by
        a future callback (or inline for immediate values)."""
        try:
            result = handler(payload)
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            if kind != _ONEWAY:
                self._write_reply(writer, write_lock,
                                  (_ERR, msg_id, method, e))
            else:
                logger.exception("oneway fast handler %s failed", method)
            return
        if isinstance(result, asyncio.Future):
            if kind == _ONEWAY:
                # Nobody consumes the reply: still release any raw
                # payload's resources (e.g. a served chunk's pin).
                result.add_done_callback(_release_raw_result)
                return
            result.add_done_callback(
                lambda f: self._write_reply_of(writer, write_lock,
                                               msg_id, method, f))
            return
        if kind != _ONEWAY:
            self._write_reply(writer, write_lock,
                              (_REP, msg_id, method, result))
        elif isinstance(result, RawReply):
            result.done()

    def _write_reply_of(self, writer, write_lock, msg_id, method,
                        fut: asyncio.Future):
        try:
            msg = (_REP, msg_id, method, fut.result())
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            msg = (_ERR, msg_id, method, e)
        self._write_reply(writer, write_lock, msg)

    def _write_reply(self, writer, write_lock, msg):
        if isinstance(msg[3], RawReply):
            data = msg[3].data
            try:
                # Two writes, both synchronous: the transport consumes
                # the payload view before returning, so a shared-memory
                # window is safe to hand over without copying.
                writer.write(_encode_raw_head(msg[0], msg[1], msg[2],
                                              len(data)))
                writer.write(data)
                if writer.transport.get_write_buffer_size() > \
                        _DRAIN_THRESHOLD:
                    _spawn(self._drain_locked(writer, write_lock))
            except (ConnectionResetError, BrokenPipeError):
                pass
            finally:
                msg[3].done()
            return
        try:
            frame = _encode_frame(msg)
        except Exception:  # noqa: BLE001 — unpicklable error payload
            frame = _encode_frame((_ERR, msg[1], msg[2],
                                   RpcError(repr(msg[3]))))
        try:
            writer.write(frame)
            if writer.transport.get_write_buffer_size() > _DRAIN_THRESHOLD:
                _spawn(self._drain_locked(writer, write_lock))
        except (ConnectionResetError, BrokenPipeError):
            pass

    @staticmethod
    async def _drain_locked(writer, write_lock):
        try:
            async with write_lock:
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

    async def _dispatch(self, writer, write_lock, kind, msg_id, method,
                        payload):
        handler = self._routes.get(method)
        try:
            if handler is None:
                raise RpcError(f"no route for method {method!r}")
            result = await handler(payload)
            if kind == _ONEWAY:
                if isinstance(result, RawReply):
                    result.done()
                return
            if isinstance(result, RawReply):
                # NOTE: an await boundary separates the handler from
                # this write, so async raw replies must carry bytes
                # (not live arena views — those are fast-route only).
                self._write_reply(writer, write_lock,
                                  (_REP, msg_id, method, result))
                return
            frame = _encode_frame((_REP, msg_id, method, result))
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            if kind == _ONEWAY:
                logger.exception("oneway handler %s failed", method)
                return
            try:
                frame = _encode_frame((_ERR, msg_id, method, e))
            except Exception:
                frame = _encode_frame((_ERR, msg_id, method, RpcError(repr(e))))
        try:
            # Fast path mirrors RpcClient._write_frame: plain write when
            # the transport buffer is shallow, locked drain only under
            # back-pressure (concurrent drains are unsafe when paused).
            writer.write(frame)
            if writer.transport.get_write_buffer_size() > _DRAIN_THRESHOLD:
                async with write_lock:
                    await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

    def stop(self):
        if self._server is not None:
            async def _close(server):
                server.close()
                # 3.12 wait_closed() waits for every open CONNECTION,
                # not just the listening socket — peers keep theirs open
                # (pooled clients), so an unbounded wait stalls every
                # shutdown for the full run_coro timeout.  Closing the
                # listener is what matters; give stragglers a beat.
                try:
                    await asyncio.wait_for(server.wait_closed(), 0.2)
                except asyncio.TimeoutError:
                    pass

            try:
                self._io.run_coro(_close(self._server), timeout=2)
            except Exception:
                pass
            self._server = None


# -------------------------------------------------------------------- client

class RpcClient:
    """Connection to one RpcServer; safe to call from any thread."""

    _counter = itertools.count()

    def __init__(self, address: str):
        self.address = address
        self._io = IoThread.get()
        self._writer: asyncio.StreamWriter | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._conn_lock: asyncio.Lock | None = None
        # Serializes write+drain: concurrent drains on one StreamWriter are
        # unsafe once the transport pauses (see server-side note).  Lock
        # acquisition is FIFO, so sequential senders keep their send order.
        self._write_lock: asyncio.Lock | None = None
        # (frame, reply-future) pairs deferred by send_request(defer=True),
        # written in one syscall by flush_deferred() (pipelined task
        # pushes); discard_deferred() fails the futures of frames that
        # were never shipped so callers can retry instead of hanging.
        self._outbox: list[tuple[bytes, asyncio.Future]] = []
        self._chaos = _ChaosInjector(
            global_config().testing_rpc_failure,
            latency_spec=global_config().testing_rpc_latency_s)
        self._closed = False

    async def _ensure_connected(self):
        # Lock-free fast path: on an established connection this runs on
        # every request, and even an uncontended Lock acquire is
        # measurable at 10k calls/s.
        writer = self._writer
        if writer is not None and not writer.is_closing():
            return
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        if self._write_lock is None:
            self._write_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            host, port = self.address.rsplit(":", 1)
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, int(port)),
                    global_config().rpc_connect_timeout_s,
                )
            except (OSError, asyncio.TimeoutError) as e:
                raise RpcConnectionError(
                    f"cannot connect to {self.address}: {e}"
                ) from e
            self._writer = writer
            # Version handshake: first frame on every connection (ref:
            # schema versioning — mixed-version peers fail fast with an
            # actionable error, not a pickle explosion mid-call).
            # Sentinel id -1: a pre-handshake server would dispatch
            # "__hello__" as a normal request and reply an error frame —
            # which must not collide with a real pending msg_id (the
            # shared counter starts at 0).
            writer.write(_encode_frame(
                (_HELLO, -1, "__hello__", {"proto": PROTOCOL_VERSION})))
            _spawn(self._read_loop(reader))

    async def _read_loop(self, reader):
        version_err = None
        try:
            while True:
                kind, msg_id, _method, payload = await _read_frame(reader)
                if kind == _GOODBYE:
                    version_err = RpcError(
                        f"{self.address} rejected this process: "
                        f"{(payload or {}).get('reason', 'version fence')}"
                        " — upgrade the older side")
                    return
                fut = self._pending.get(msg_id)
                if fut is None or fut.done():
                    continue
                if kind == _ERR:
                    fut.set_exception(
                        payload if isinstance(payload, BaseException)
                        else RpcError(str(payload))
                    )
                else:
                    fut.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._writer = None
            # Deferred frames must not survive into a reconnected writer
            # (replaying a stale PushTask double-executes the task).
            self.discard_deferred()
            err = version_err or RpcConnectionError(
                f"connection to {self.address} lost")
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(err)
            self._pending.clear()

    async def send_request(self, method: str, payload: Any = None,
                           defer: bool = False) -> asyncio.Future:
        """Write the request frame now; return the future for the reply.

        Callers needing strict send ordering (e.g. per-actor task queues)
        await this sequentially and await the reply futures separately, so
        ordering and pipelining compose.

        ``defer=True`` queues the frame in the client outbox instead of
        writing; a later :meth:`flush_deferred` ships every queued frame
        in one transport write (one syscall for a pipeline burst).
        """
        if self._chaos.should_fail(method):
            raise RpcConnectionError(f"[chaos] injected failure for {method}")
        delay = self._chaos.delay_for(method)
        if delay > 0:
            await asyncio.sleep(delay)
        await self._ensure_connected()
        msg_id = next(self._counter)
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        # Cleanup on any terminal state — including cancellation by a
        # wait_for timeout — so abandoned calls never leak their entry.
        fut.add_done_callback(
            lambda _f, mid=msg_id: self._pending.pop(mid, None))
        frame = _encode_frame((_REQ, msg_id, method, payload))
        if defer:
            self._outbox.append((frame, fut))
            return fut
        await self._write_frame(frame)
        return fut

    async def _write_frame(self, frame: bytes):
        """Write with flow control: the common case (transport buffer
        under the threshold) is a plain non-awaiting write; only a
        backed-up transport pays the drain await (and its lock)."""
        writer = self._writer
        if writer is None:
            raise RpcConnectionError(f"connection to {self.address} lost")
        writer.write(frame)
        if writer.transport.get_write_buffer_size() > _DRAIN_THRESHOLD:
            async with self._write_lock:
                writer = self._writer
                if writer is None:
                    raise RpcConnectionError(
                        f"connection to {self.address} lost")
                await writer.drain()

    async def flush_deferred(self):
        """Ship all defer-queued frames in a single transport write."""
        if not self._outbox:
            return
        entries, self._outbox = self._outbox, []
        try:
            await self._write_frame(entries[0][0] if len(entries) == 1
                                    else b"".join(f for f, _ in entries))
        except BaseException:
            self._fail_entries(entries)
            raise

    def discard_deferred(self):
        """Drop never-shipped deferred frames, failing their futures —
        replaying them on a later (re)connection would double-execute
        tasks that the caller already rerouted elsewhere."""
        entries, self._outbox = self._outbox, []
        self._fail_entries(entries)

    def _fail_entries(self, entries):
        err = RpcConnectionError(
            f"request to {self.address} was never sent")
        for _frame, fut in entries:
            if not fut.done():
                fut.set_exception(err)

    async def call_async(
        self, method: str, payload: Any = None, timeout: float | None = None
    ) -> Any:
        # Tracing fast path: one contextvar read.  Calls made inside a
        # sampled trace (the caller's context rides into this coroutine
        # via the event-loop context copy) record a client span with a
        # serialize/wire stage split; everything else takes the bare
        # path below untouched.
        ctx = _trace_current()
        if ctx is not None:
            return await self._traced_call(ctx, method, payload, timeout)
        fut = await self.send_request(method, payload)
        return await self._await_reply(fut, method, timeout)

    async def _await_reply(self, fut, method: str,
                           timeout: float | None) -> Any:
        """ONE deadline semantic for traced and untraced calls:
        ``timeout <= 0`` is the explicit no-deadline escape hatch
        (long-running task pushes); None takes the config default."""
        if timeout is None:
            timeout = global_config().rpc_call_timeout_s
        if timeout <= 0:
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError as e:
            raise RpcTimeoutError(
                f"{method} to {self.address} timed out") from e

    async def _traced_call(self, ctx, method: str, payload: Any,
                           timeout: float | None) -> Any:
        """call_async under a sampled trace context: record an
        ``rpc:{method}`` client span (stages: serialize = encode+write,
        wire = flight + server time) and feed the
        ``art_rpc_latency_s{method,stage}`` histogram with the trace id
        as its exemplar."""
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        t_wall = time.time()
        t0 = time.perf_counter()
        t_sent = t0
        err = False
        try:
            fut = await self.send_request(method, payload)
            t_sent = time.perf_counter()
            return await self._await_reply(fut, method, timeout)
        except BaseException:
            err = True
            raise
        finally:
            t_end = time.perf_counter()
            stages = {"serialize": t_sent - t0, "wire": t_end - t_sent}
            sid = tracing_plane.record_span(
                ctx, f"rpc:{method}", ts=t_wall, dur_s=t_end - t0,
                stages=stages,
                attrs={"method": method, "peer": self.address},
                error=err, service="rpc-client")
            if sid is not None:
                tracing_plane.record_rpc(method, stages, ctx.trace_id)

    async def oneway_async(self, method: str, payload: Any = None) -> None:
        await self._ensure_connected()
        await self._write_frame(_encode_frame((_ONEWAY, -1, method, payload)))

    def call(self, method: str, payload: Any = None,
             timeout: float | None = None, retries: int = 0) -> Any:
        """Blocking call from any non-io thread, with connection retries."""
        from ant_ray_tpu._lint.lockcheck import note_blocking  # noqa: PLC0415

        # Runtime evidence for the static blocking-under-lock rule: if
        # the calling thread holds an instrumented lock across this
        # round trip, lockcheck reports the hold with its stack.
        note_blocking(f"RpcClient.call:{method}")
        attempt = 0
        while True:
            try:
                return self._io.run_coro(
                    self.call_async(method, payload, timeout)
                )
            except RpcConnectionError:
                attempt += 1
                if attempt > retries:
                    raise
                time.sleep(min(0.1 * 2 ** attempt, 2.0))

    def close(self):
        self._closed = True
        writer = self._writer
        if writer is not None:
            self._io.call_soon(writer.close)
            self._writer = None


class ClientPool:
    """Shared RpcClients keyed by address (ref: rpc client pools)."""

    def __init__(self):
        self._clients: dict[str, RpcClient] = {}
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._lock = make_lock("rpc.client_pool")

    def get(self, address: str) -> RpcClient:
        with self._lock:
            client = self._clients.get(address)
            if client is None or client._closed:
                if "," in address:
                    # A comma-joined replica list is a GCS HA spec: the
                    # pool hands back a leader-aware router with the
                    # RpcClient call surface, so every existing
                    # ``pool.get(gcs_address)`` call site gains
                    # redirect-following + re-resolve failover without
                    # changing.  (Import here: gcs_client imports this
                    # module.)
                    from ant_ray_tpu._private.gcs_client import (  # noqa: PLC0415
                        GcsRouter,
                    )

                    client = GcsRouter(address, self)
                else:
                    client = RpcClient(address)
                self._clients[address] = client
            return client

    def invalidate(self, address: str) -> None:
        with self._lock:
            client = self._clients.pop(address, None)
        if client is not None:
            client.close()

    def close_all(self):
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
