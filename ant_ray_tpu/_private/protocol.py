"""Asyncio RPC substrate for the control plane.

Role-equivalent to the reference's gRPC wrappers
(ref: src/ray/rpc/grpc_server.h, retryable_grpc_client.h, rpc_chaos.h) with a
lighter transport: length-prefixed pickle frames over TCP, one shared
background IO thread per process (the analogue of the instrumented asio
io_context, ref: src/ray/common/asio/).  The public surface — ``RpcServer``
with async method handlers, ``RpcClient.call`` with retries and deadline, and
deterministic chaos fault injection — is transport-agnostic so it can be
re-hosted on gRPC without touching callers.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import random
import socket
import threading
import time
from typing import Any, Awaitable, Callable

from ant_ray_tpu._private import hotframe
from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.specs import TaskSpec

logger = logging.getLogger(__name__)


def _trace_current():
    """Sampled trace context active in this task, or None.  Lazy-bound:
    the tracing plane imports config (not protocol), so binding at
    first use avoids ordering surprises during package init."""
    global _trace_current
    from ant_ray_tpu.observability.tracing_plane import (  # noqa: PLC0415
        current_sampled,
    )

    _trace_current = current_sampled
    return current_sampled()

_REQ, _REP, _ERR, _ONEWAY, _HELLO, _GOODBYE = 0, 1, 2, 3, 4, 5
# Pseudo-kind yielded by _read_frame for hot-flagged frames: the body
# is handed to the hot-frame codec undecoded (the per-connection
# template table lives with the caller, not the reader).
_HOT = 6

# Wire protocol version (ref: protobuf schema versioning — the pickled
# tuple frames are a fixed contract per version; mixed-version nodes
# fail fast at connect with a clear error instead of corrupting state
# mid-RPC).  Bump on any change to frame shapes or payload contracts;
# see wire_schema.py for the per-method payload registry.
PROTOCOL_VERSION = 1

_HEADER = 8  # u64 big-endian frame length

# Top header bit marks a RAW frame: the body is
# ``u32 meta_len | pickle((kind, msg_id, method, None)) | payload bytes``
# and the payload is handed to the caller as a memoryview over the read
# buffer instead of travelling through pickle.  Raw frames are only ever
# sent in REPLY to a method that opts in (ReadChunkRaw), so the change
# is additive within PROTOCOL_VERSION — peers that never ask never see
# one.
_RAW_FLAG = 1 << 63

# Second header bit marks a HOT frame (hotframe.py): the body is a
# compact struct-packed PushTask call / template / batched-ack record
# set that never round-trips through pickle.  Hot frames are only ever
# sent to peers that advertised ``hot`` in the HELLO handshake (and
# were acked), so the change is additive within PROTOCOL_VERSION —
# peers that never negotiated never see one.
_HOT_FLAG = 1 << 62
_LEN_MASK = _HOT_FLAG - 1


class RawReply:
    """Handler-return wrapper: reply with ``data`` as a raw out-of-band
    frame (no pickle copy of the payload).  ``data`` may be bytes or a
    memoryview; it is consumed synchronously by the transport write, so
    views into shared memory are safe as long as the handler returns on
    the io loop without an intervening await (fast routes).

    ``release`` (optional) is invoked exactly once after the transport
    consumed the payload (or the reply was dropped) — handlers use it
    to unpin shared-memory windows they served from."""

    __slots__ = ("data", "release")

    def __init__(self, data, release=None):
        self.data = data
        self.release = release

    def done(self) -> None:
        release, self.release = self.release, None
        if release is not None:
            try:
                release()
            except Exception:  # noqa: BLE001 — reply path must not die
                logger.exception("RawReply release hook failed")

# Transport write-buffer level above which senders await drain (flow
# control); below it, frames are written inline with no await.  Shared by
# client sends and server replies.
_DRAIN_THRESHOLD = 1 << 20


class RpcError(Exception):
    pass


class RpcConnectionError(RpcError):
    pass


class RpcTimeoutError(RpcError):
    pass


class NotLeaderError(RpcError):
    """A GCS mutation reached a replica that is not (or no longer) the
    leader.  Carries the leader's address when the replica knows it, so
    the client-side router (gcs_client.GcsRouter) can redirect instead
    of surfacing "no route".  Raised server-side by the HA mutation
    guard; travels the wire pickled like any handler exception."""

    def __init__(self, leader_addr: str = ""):
        super().__init__(
            "not the GCS leader"
            + (f" (leader at {leader_addr})" if leader_addr
               else " (no leader elected yet)"))
        self.leader_addr = leader_addr

    def __reduce__(self):
        # Default exception pickling replays ``args`` (the formatted
        # message) into __init__, which would masquerade as an address.
        return (NotLeaderError, (self.leader_addr,))


class _ChaosInjector:
    """Deterministic RPC fault injection (ref: src/ray/rpc/rpc_chaos.h:24).

    Config string: ``"method:prob,method2:prob"``; seeded RNG so failures are
    reproducible across runs with the same seed.  A ``seed:<n>`` entry in the
    spec overrides the ``seed`` argument — the channel the chaos harness
    (util/chaos.py) uses to carry its schedule seed through ``_system_config``
    into every daemon's injector.
    """

    def __init__(self, spec: str, seed: int = 0, latency_spec: str = ""):
        self._probs: dict[str, float] = {}
        for part in filter(None, (spec or "").split(",")):
            method, prob = part.split(":")
            if method == "seed":
                seed = int(float(prob))
                continue
            self._probs[method] = float(prob)
        self._rng = random.Random(seed)
        # Per-method injected latency (testing_rpc_latency_s): applied
        # client-side before the request frame is written — the
        # deterministic stand-in for a slow replica / congested link.
        self._delays: dict[str, float] = {}
        for part in filter(None, (latency_spec or "").split(",")):
            method, secs = part.split(":")
            if method == "seed":
                continue
            self._delays[method] = float(secs)

    def should_fail(self, method: str) -> bool:
        prob = self._probs.get(method, 0.0)
        return prob > 0 and self._rng.random() < prob

    def delay_for(self, method: str) -> float:
        return self._delays.get(method, 0.0) if self._delays else 0.0


# ------------------------------------------------------------------- io loop

class IoThread:
    """One background asyncio loop per process; all servers/clients share it."""

    _instance: "IoThread | None" = None
    _lock = threading.Lock()

    def __init__(self):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(
            target=self._run, name="art-io", daemon=True
        )
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    @classmethod
    def get(cls) -> "IoThread":
        with cls._lock:
            if cls._instance is None or not cls._instance._thread.is_alive():
                cls._instance = cls()
            return cls._instance

    def run_coro(self, coro: Awaitable, timeout: float | None = None) -> Any:
        """Run a coroutine on the io loop from a foreign thread, blocking."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_soon(self, fn: Callable, *args) -> None:
        self.loop.call_soon_threadsafe(fn, *args)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            inst, cls._instance = cls._instance, None
        if inst is not None:
            inst.loop.call_soon_threadsafe(inst.loop.stop)


def _release_raw_result(fut: "asyncio.Future") -> None:
    try:
        result = fut.result()
    except Exception:  # noqa: BLE001 — handler error, nothing to free
        return
    if isinstance(result, RawReply):
        result.done()


# asyncio's loop keeps only weak refs to tasks; hold strong refs here so
# fire-and-forget dispatch/read-loop tasks are never GC'd mid-flight.
_background_tasks: set = set()


def _spawn(coro) -> None:
    task = asyncio.ensure_future(coro)
    _background_tasks.add(task)
    task.add_done_callback(_background_tasks.discard)


# ------------------------------------------------- wire cost accounting
#
# (method, direction) -> [frames, bytes, encode_ns]: this process's
# cumulative control-plane wire cost, per wire_schema method.  The
# hotframe.counters idiom — a module dict mutated without a lock; each
# list-slot += is a handful of GIL-serialized bytecodes, and a lost
# increment under a rare interleave is acceptable for accounting that
# exists to rank methods by cost.  The profiler's publish tick rolls the
# deltas up into art_rpc_bytes_total / art_rpc_frames_total through
# MetricRecord (see observability/cpu_profiler.py), so per-node
# control-plane cost is a scrapeable series.

wire_counters: dict = {}
_wire_published: dict = {}


def _wire_account(method: str, direction: str, nbytes: int,
                  encode_ns: int = 0, conn_stats: dict | None = None):
    key = (method, direction)
    entry = wire_counters.get(key)
    if entry is None:
        entry = wire_counters.setdefault(key, [0, 0, 0])
    entry[0] += 1
    entry[1] += nbytes
    entry[2] += encode_ns
    if conn_stats is not None:
        conn_entry = conn_stats.get(key)
        if conn_entry is None:
            conn_entry = conn_stats.setdefault(key, [0, 0, 0])
        conn_entry[0] += 1
        conn_entry[1] += nbytes
        conn_entry[2] += encode_ns


def wire_deltas() -> dict:
    """(method, direction) -> (frames, bytes, encode_ns) accumulated
    since the previous call.  Single-consumer by design: the process's
    profiler publish tick owns the delta cursor; tests and debuggers
    read ``wire_counters`` directly."""
    out = {}
    for key, entry in list(wire_counters.items()):
        totals = (entry[0], entry[1], entry[2])
        last = _wire_published.get(key, (0, 0, 0))
        delta = (totals[0] - last[0], totals[1] - last[1],
                 totals[2] - last[2])
        if any(delta):
            out[key] = delta
            _wire_published[key] = totals
    return out


# Server-side handle time, the other half of the cost picture: wire
# accounting says what a method moves, handle accounting says what it
# COSTS the serving loop — dispatch→reply-encoded ns per method (async
# routes) or the sync handler call itself (fast routes, where chasing
# Future completion would tax the PushTask hot path with a callback).
# Same lock-free module-global idiom as wire_counters above.

handle_counters: dict = {}
_handle_published: dict = {}


def _handle_account(method: str, handle_ns: int) -> None:
    entry = handle_counters.get(method)
    if entry is None:
        entry = handle_counters.setdefault(method, [0, 0])
    entry[0] += 1
    entry[1] += handle_ns


def handle_deltas() -> dict:
    """method -> (calls, handle_ns) accumulated since the previous
    call.  Single-consumer cursor, like :func:`wire_deltas`."""
    out = {}
    for method, entry in list(handle_counters.items()):
        totals = (entry[0], entry[1])
        last = _handle_published.get(method, (0, 0))
        delta = (totals[0] - last[0], totals[1] - last[1])
        if any(delta):
            out[method] = delta
            _handle_published[method] = totals
    return out


async def _read_frame(reader: asyncio.StreamReader) -> Any:
    """One frame off the wire: ``(kind, msg_id, method, payload,
    nbytes)`` — nbytes is the full on-wire size (header included), the
    recv side of wire accounting."""
    header = await reader.readexactly(_HEADER)
    length = int.from_bytes(header, "big")
    if length & _HOT_FLAG:
        # Hand the body over undecoded: hot-frame decode needs the
        # per-connection template table, which the caller owns.
        body_len = length & _LEN_MASK
        data = await reader.readexactly(body_len)
        return _HOT, -1, "", data, _HEADER + body_len
    if length & _RAW_FLAG:
        body_len = length & ~_RAW_FLAG
        data = await reader.readexactly(body_len)
        meta_len = int.from_bytes(data[:4], "big")
        kind, msg_id, method, _ = pickle.loads(data[4:4 + meta_len])
        # Zero-copy hand-off: a view over the (immutable) read buffer.
        return (kind, msg_id, method, memoryview(data)[4 + meta_len:],
                _HEADER + body_len)
    data = await reader.readexactly(length)
    kind, msg_id, method, payload = pickle.loads(data)
    return kind, msg_id, method, payload, _HEADER + length


def _encode_frame(msg: Any) -> bytes:
    data = pickle.dumps(msg, protocol=5)
    return len(data).to_bytes(_HEADER, "big") + data


def _encode_hot_frame(body: bytes) -> bytes:
    """Frame one hot-codec body (hotframe.py encodes bodies only; the
    transport header lives here with its sibling flags)."""
    return (len(body) | _HOT_FLAG).to_bytes(_HEADER, "big") + body


class _HotSendState:
    """Per-connection hot-wire send state: established when the peer's
    HELLO-ack lands, discarded with the connection (``writer`` is the
    generation tag — a reconnect invalidates templates the new peer
    never saw, so stale state must never outlive its socket)."""

    __slots__ = ("writer", "version", "templates")

    def __init__(self, writer, version: int):
        self.writer = writer
        self.version = version
        self.templates = hotframe.TemplateCache()


class _ServerConn:
    """Per-connection server state: the receiver half of the template
    cache plus the coalesced-ack buffer (one flush = one frame carrying
    every reply that completed in the same io-loop tick)."""

    __slots__ = ("writer", "write_lock", "templates", "acks",
                 "flush_scheduled", "wire_stats")

    def __init__(self, writer, write_lock):
        self.writer = writer
        self.write_lock = write_lock
        self.templates: dict[int, tuple] = {}
        self.acks: list[bytes] = []
        self.flush_scheduled = False
        # Per-connection (method, direction) -> [frames, bytes,
        # encode_ns], mirrored into the module-level rollup.
        self.wire_stats: dict = {}


def _encode_raw_head(kind: int, msg_id: int, method: str,
                     payload_len: int) -> bytes:
    """Header + meta for a raw frame; the payload bytes are written
    separately by the caller (so an arena view never round-trips
    through pickle)."""
    meta = pickle.dumps((kind, msg_id, method, None), protocol=5)
    total = 4 + len(meta) + payload_len
    return ((total | _RAW_FLAG).to_bytes(_HEADER, "big")
            + len(meta).to_bytes(4, "big") + meta)


# -------------------------------------------------------------------- server

class RpcServer:
    """Async RPC server. Handlers: ``async def h(payload) -> reply``.

    Register with :meth:`route`; a handler raising propagates the exception to
    the caller (pickled, re-raised client-side as its original type).
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._host = host
        self._port = port
        self._routes: dict[str, Callable[[Any], Awaitable[Any]]] = {}
        # Sync handlers returning a value or a Future: dispatched without
        # creating a coroutine/Task per request — the hot-path shape for
        # task execution (handler enqueues to an executor and returns its
        # reply future).
        self._fast_routes: dict[str, Callable[[Any], Any]] = {}
        self._server: asyncio.AbstractServer | None = None
        self._io = IoThread.get()
        self.address: str = ""
        # Per-instance hot-wire gate (seeded from config): never ack a
        # client's hot advertisement when off — the seam mixed-version
        # interop tests flip to stand in for a pre-hot-wire server.
        self._hot_enabled = global_config().hot_wire_enabled

    def route(self, method: str, handler: Callable[[Any], Awaitable[Any]]):
        self._routes[method] = handler

    def routes(self, handlers: dict[str, Callable]):
        self._routes.update(handlers)

    def fast_route(self, method: str, handler: Callable[[Any], Any]):
        """Register a SYNC handler (may return an asyncio.Future)."""
        self._fast_routes[method] = handler

    def start(self) -> str:
        self._io.run_coro(self._start())
        return self.address

    async def _start(self):
        self._server = await asyncio.start_server(
            self._handle_conn, self._host, self._port
        )
        port = self._server.sockets[0].getsockname()[1]
        self.address = f"{self._host}:{port}"

    async def _handle_conn(self, reader, writer):
        # One write lock per connection: replies are written by concurrently
        # dispatched handler tasks, and StreamWriter.drain() is not safe to
        # call from two coroutines at once when flow control pauses the
        # transport (FlowControlMixin._drain_helper asserts).
        write_lock = asyncio.Lock()
        conn = _ServerConn(writer, write_lock)
        try:
            while True:
                try:
                    kind, msg_id, method, payload, nbytes = \
                        await _read_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return
                # Hot frames carry no method name on the wire — that is
                # the point of the template cache — but every hot call
                # is a PushTask by contract, so the accounting stays
                # per-method.
                _wire_account("PushTask" if kind == _HOT else method,
                              "recv", nbytes, conn_stats=conn.wire_stats)
                if kind == _HELLO:
                    peer = (payload or {}).get("proto")
                    if peer != PROTOCOL_VERSION:
                        # Version fence: reply GOODBYE (so the client
                        # fails every call with a clear upgrade message)
                        # and drop the connection.
                        self._write_reply(
                            writer, write_lock,
                            (_GOODBYE, msg_id, method,
                             {"proto": PROTOCOL_VERSION,
                              "reason": f"peer wire protocol v{peer} is "
                                        f"not v{PROTOCOL_VERSION}"}))
                        return
                    # Hot-wire negotiation (additive within the
                    # version): a peer advertising ``hot`` gets an ack
                    # and MAY then send hot frames; a peer that never
                    # advertises (older build, or hot_wire_enabled
                    # off) never hears back and stays fully pickled.
                    if (payload or {}).get("hot") and self._hot_enabled:
                        self._write_reply(
                            writer, write_lock,
                            (_HELLO, -1, "__hello__",
                             {"proto": PROTOCOL_VERSION,
                              "hot": hotframe.HOT_WIRE_VERSION}))
                    continue
                if kind == _HOT:
                    self._dispatch_hot(conn, payload)
                    continue
                fast = self._fast_routes.get(method)
                if fast is not None:
                    self._dispatch_fast(writer, write_lock, kind, msg_id,
                                        method, payload, fast)
                    continue
                _spawn(
                    self._dispatch(
                        writer, write_lock, kind, msg_id, method, payload)
                )
        finally:
            writer.close()

    def _dispatch_fast(self, writer, write_lock, kind, msg_id, method,
                       payload, handler):
        """Task-free dispatch for sync handlers: the reply is written by
        a future callback (or inline for immediate values).  Handle
        accounting times only the sync handler call — for a handler
        that returns a Future the queue/execute tail is the worker's
        cost, not this io loop's, and chasing completion would add a
        callback to the hottest path on the wire."""
        h0 = time.perf_counter_ns()
        try:
            result = handler(payload)
            _handle_account(method, time.perf_counter_ns() - h0)
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            if kind != _ONEWAY:
                self._write_reply(writer, write_lock,
                                  (_ERR, msg_id, method, e))
            else:
                logger.exception("oneway fast handler %s failed", method)
            return
        if isinstance(result, asyncio.Future):
            if kind == _ONEWAY:
                # Nobody consumes the reply: still release any raw
                # payload's resources (e.g. a served chunk's pin).
                result.add_done_callback(_release_raw_result)
                return
            result.add_done_callback(
                lambda f: self._write_reply_of(writer, write_lock,
                                               msg_id, method, f))
            return
        if kind != _ONEWAY:
            self._write_reply(writer, write_lock,
                              (_REP, msg_id, method, result))
        elif isinstance(result, RawReply):
            result.done()

    # ------------------------------------------------------ hot dispatch

    def _dispatch_hot(self, conn: _ServerConn, body) -> None:
        """Task-free dispatch of one hot frame (io loop).  A HOT_CALL
        maps to the PushTask fast route by contract; its reply is
        queued into the connection's coalesced-ack batch instead of
        going out as its own frame."""
        hkind = body[0] if body else -1
        if hkind == hotframe.HOT_TEMPLATE:
            try:
                tid, fields = hotframe.decode_template(body)
            except hotframe.HotFrameError as e:
                logger.warning("dropped undecodable hot template: %s", e)
                return
            conn.templates[tid] = fields
            return
        if hkind != hotframe.HOT_CALL:
            logger.warning("dropped hot frame of unknown kind %r", hkind)
            return
        try:
            msg_id, spec = hotframe.decode_call(body, conn.templates)
        except hotframe.HotFrameError as e:
            if e.msg_id is not None:
                # The head parsed: fail THAT call instead of leaving
                # its future to hang client-side.
                self._queue_hot_ack(conn, hotframe.encode_ack_exc(
                    e.msg_id, RpcError(str(e))))
            else:
                logger.warning("dropped undecodable hot call: %s", e)
            return
        handler = self._fast_routes.get("PushTask")
        if handler is None:
            self._queue_hot_ack(conn, hotframe.encode_ack_exc(
                msg_id, RpcError("no route for method 'PushTask'")))
            return
        try:
            result = handler(spec)
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            self._queue_hot_ack(conn, hotframe.encode_ack_exc(msg_id, e))
            return
        if isinstance(result, asyncio.Future):
            # Context rides ON the future (preallocated tuple + one
            # shared bound method) — no closure per call.
            result._art_hot_ctx = (conn, msg_id)
            result.add_done_callback(self._hot_ack_cb)
        else:
            self._queue_hot_reply(conn, msg_id, result)

    def _hot_ack_cb(self, fut: asyncio.Future) -> None:
        conn, msg_id = fut._art_hot_ctx
        try:
            reply = fut.result()
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            self._queue_hot_ack(conn, hotframe.encode_ack_exc(msg_id, e))
            return
        self._queue_hot_reply(conn, msg_id, reply)

    def _queue_hot_reply(self, conn: _ServerConn, msg_id: int, reply):
        rec = hotframe.encode_ack(msg_id, reply)
        if rec is None:
            # Unknown reply shape: fall back to a pickled reply frame
            # for just this call — the client resolves futures by
            # msg_id on either path, so mixing is safe.
            self._write_reply(conn.writer, conn.write_lock,
                              (_REP, msg_id, "PushTask", reply))
            return
        self._queue_hot_ack(conn, rec)

    def _queue_hot_ack(self, conn: _ServerConn, rec: bytes) -> None:
        conn.acks.append(rec)
        if not conn.flush_scheduled:
            conn.flush_scheduled = True
            self._io.loop.call_soon(self._flush_hot_acks, conn)

    def _flush_hot_acks(self, conn: _ServerConn) -> None:
        """One frame, N acks: every reply completed since the last tick
        ships in a single transport write."""
        conn.flush_scheduled = False
        if not conn.acks:
            return
        records, conn.acks = conn.acks, []
        t0 = time.perf_counter_ns()
        frame = _encode_hot_frame(hotframe.frame_acks(records))
        _wire_account("PushTask", "send", len(frame),
                      time.perf_counter_ns() - t0, conn.wire_stats)
        try:
            conn.writer.write(frame)
            if conn.writer.transport.get_write_buffer_size() > \
                    _DRAIN_THRESHOLD:
                _spawn(self._drain_locked(conn.writer, conn.write_lock))
        except (ConnectionResetError, BrokenPipeError):
            pass

    def _write_reply_of(self, writer, write_lock, msg_id, method,
                        fut: asyncio.Future):
        try:
            msg = (_REP, msg_id, method, fut.result())
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            msg = (_ERR, msg_id, method, e)
        self._write_reply(writer, write_lock, msg)

    def _write_reply(self, writer, write_lock, msg):
        if isinstance(msg[3], RawReply):
            data = msg[3].data
            try:
                # Two writes, both synchronous: the transport consumes
                # the payload view before returning, so a shared-memory
                # window is safe to hand over without copying.
                head = _encode_raw_head(msg[0], msg[1], msg[2],
                                        len(data))
                _wire_account(msg[2], "send", len(head) + len(data))
                writer.write(head)
                writer.write(data)
                if writer.transport.get_write_buffer_size() > \
                        _DRAIN_THRESHOLD:
                    _spawn(self._drain_locked(writer, write_lock))
            except (ConnectionResetError, BrokenPipeError):
                pass
            finally:
                msg[3].done()
            return
        t0 = time.perf_counter_ns()
        try:
            frame = _encode_frame(msg)
        except Exception:  # noqa: BLE001 — unpicklable error payload
            frame = _encode_frame((_ERR, msg[1], msg[2],
                                   RpcError(repr(msg[3]))))
        _wire_account(msg[2], "send", len(frame),
                      time.perf_counter_ns() - t0)
        try:
            writer.write(frame)
            if writer.transport.get_write_buffer_size() > _DRAIN_THRESHOLD:
                _spawn(self._drain_locked(writer, write_lock))
        except (ConnectionResetError, BrokenPipeError):
            pass

    @staticmethod
    async def _drain_locked(writer, write_lock):
        try:
            async with write_lock:
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

    async def _dispatch(self, writer, write_lock, kind, msg_id, method,
                        payload):
        handler = self._routes.get(method)
        h0 = time.perf_counter_ns()
        try:
            if handler is None:
                raise RpcError(f"no route for method {method!r}")
            result = await handler(payload)
            if kind == _ONEWAY:
                _handle_account(method, time.perf_counter_ns() - h0)
                if isinstance(result, RawReply):
                    result.done()
                return
            if isinstance(result, RawReply):
                # NOTE: an await boundary separates the handler from
                # this write, so async raw replies must carry bytes
                # (not live arena views — those are fast-route only).
                _handle_account(method, time.perf_counter_ns() - h0)
                self._write_reply(writer, write_lock,
                                  (_REP, msg_id, method, result))
                return
            t0 = time.perf_counter_ns()
            frame = _encode_frame((_REP, msg_id, method, result))
            t1 = time.perf_counter_ns()
            _wire_account(method, "send", len(frame), t1 - t0)
            _handle_account(method, t1 - h0)
        except Exception as e:  # noqa: BLE001 — forwarded to caller
            if kind == _ONEWAY:
                logger.exception("oneway handler %s failed", method)
                return
            try:
                frame = _encode_frame((_ERR, msg_id, method, e))
            except Exception:
                frame = _encode_frame((_ERR, msg_id, method, RpcError(repr(e))))
            _wire_account(method, "send", len(frame))
            _handle_account(method, time.perf_counter_ns() - h0)
        try:
            # Fast path mirrors RpcClient._write_frame: plain write when
            # the transport buffer is shallow, locked drain only under
            # back-pressure (concurrent drains are unsafe when paused).
            writer.write(frame)
            if writer.transport.get_write_buffer_size() > _DRAIN_THRESHOLD:
                async with write_lock:
                    await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

    def stop(self):
        if self._server is not None:
            async def _close(server):
                server.close()
                # 3.12 wait_closed() waits for every open CONNECTION,
                # not just the listening socket — peers keep theirs open
                # (pooled clients), so an unbounded wait stalls every
                # shutdown for the full run_coro timeout.  Closing the
                # listener is what matters; give stragglers a beat.
                try:
                    await asyncio.wait_for(server.wait_closed(), 0.2)
                except asyncio.TimeoutError:
                    pass

            try:
                self._io.run_coro(_close(self._server), timeout=2)
            except Exception:
                pass
            self._server = None


# -------------------------------------------------------------------- client

class RpcClient:
    """Connection to one RpcServer; safe to call from any thread."""

    _counter = itertools.count()

    def __init__(self, address: str):
        self.address = address
        self._io = IoThread.get()
        self._writer: asyncio.StreamWriter | None = None
        self._pending: dict[int, asyncio.Future] = {}
        self._conn_lock: asyncio.Lock | None = None
        # Serializes write+drain: concurrent drains on one StreamWriter are
        # unsafe once the transport pauses (see server-side note).  Lock
        # acquisition is FIFO, so sequential senders keep their send order.
        self._write_lock: asyncio.Lock | None = None
        # (frame, reply-future, writer-tag) triples deferred by
        # send_request(defer=True), written in one syscall by
        # flush_deferred() (pipelined task pushes); discard_deferred()
        # fails the futures of frames that were never shipped so
        # callers can retry instead of hanging.  The writer tag is None
        # for connection-agnostic pickled frames; hot frames carry the
        # writer they were encoded for (their template ids mean nothing
        # to any other connection) and are failed instead of shipped if
        # the connection turned over before the flush.
        self._outbox: list[tuple[bytes, asyncio.Future, Any]] = []
        # Hot-wire send state, established by the server's HELLO-ack
        # and keyed to the connection it arrived on (see _HotSendState).
        self._hot: _HotSendState | None = None
        self._chaos = _ChaosInjector(
            global_config().testing_rpc_failure,
            latency_spec=global_config().testing_rpc_latency_s)
        # Chaos-free is the production shape: precomputed so the sync
        # send fast path can skip the injector entirely.
        self._chaos_active = bool(self._chaos._probs
                                  or self._chaos._delays)
        self._closed = False
        # Per-client (method, direction) -> [frames, bytes, encode_ns]
        # wire cost, mirrored into the module-level rollup (survives
        # reconnects: the unit of attribution is the peer, not the
        # socket generation).
        self.wire_stats: dict = {}
        # Shared done-callback for pending-entry cleanup (a per-call
        # lambda with a default-arg cell allocates a closure each).
        self._pop_pending_cb = self._pop_pending

    async def _ensure_connected(self):
        # Lock-free fast path: on an established connection this runs on
        # every request, and even an uncontended Lock acquire is
        # measurable at 10k calls/s.
        writer = self._writer
        if writer is not None and not writer.is_closing():
            return
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        if self._write_lock is None:
            self._write_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            host, port = self.address.rsplit(":", 1)
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(host, int(port)),
                    global_config().rpc_connect_timeout_s,
                )
            except (OSError, asyncio.TimeoutError) as e:
                raise RpcConnectionError(
                    f"cannot connect to {self.address}: {e}"
                ) from e
            self._writer = writer
            # Version handshake: first frame on every connection (ref:
            # schema versioning — mixed-version peers fail fast with an
            # actionable error, not a pickle explosion mid-call).
            # Sentinel id -1: a pre-handshake server would dispatch
            # "__hello__" as a normal request and reply an error frame —
            # which must not collide with a real pending msg_id (the
            # shared counter starts at 0).
            hello = {"proto": PROTOCOL_VERSION}
            if global_config().hot_wire_enabled:
                # Advertise the hot wire; frames stay pickled until
                # (unless) the server's HELLO-ack lands.
                hello["hot"] = hotframe.HOT_WIRE_VERSION
            hello_frame = _encode_frame((_HELLO, -1, "__hello__", hello))
            _wire_account("__hello__", "send", len(hello_frame),
                          conn_stats=self.wire_stats)
            writer.write(hello_frame)
            _spawn(self._read_loop(reader, writer))

    async def _read_loop(self, reader, writer):
        version_err = None
        try:
            while True:
                kind, msg_id, _method, payload, nbytes = \
                    await _read_frame(reader)
                # Reply frames carry their method; coalesced hot-ack
                # frames are all PushTask replies by contract.
                _wire_account("PushTask" if kind == _HOT else _method,
                              "recv", nbytes, conn_stats=self.wire_stats)
                if kind == _GOODBYE:
                    version_err = RpcError(
                        f"{self.address} rejected this process: "
                        f"{(payload or {}).get('reason', 'version fence')}"
                        " — upgrade the older side")
                    return
                if kind == _HELLO:
                    # HELLO-ack: the peer speaks the hot wire.  Fresh
                    # template cache, keyed to THIS connection.
                    peer = (payload or {}).get("hot", 0)
                    if peer:
                        self._hot = _HotSendState(
                            writer,
                            min(peer, hotframe.HOT_WIRE_VERSION))
                    continue
                if kind == _HOT:
                    try:
                        self._on_hot_acks(payload)
                    except hotframe.HotFrameError as e:
                        # decode_acks' contract: an undecodable ack
                        # frame is a DEAD connection, not a skippable
                        # record — later record boundaries are unknown,
                        # so every reply batched behind the corruption
                        # would leave its caller hanging forever.  Kill
                        # the socket; the teardown below fails this
                        # connection's pending futures for retry.
                        version_err = RpcError(
                            f"undecodable hot ack frame from "
                            f"{self.address}: {e}")
                        writer.close()
                        return
                    continue
                fut = self._pending.get(msg_id)
                if fut is None or fut.done():
                    continue
                if kind == _ERR:
                    fut.set_exception(
                        payload if isinstance(payload, BaseException)
                        else RpcError(str(payload))
                    )
                else:
                    fut.set_result(payload)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            # Guarded teardown: a stale loop racing a completed
            # reconnect must not clobber the NEW connection's writer,
            # its negotiated hot state, OR its in-flight calls — every
            # step below is scoped to THIS loop's connection.
            if self._writer is writer:
                self._writer = None
            hot = self._hot
            if hot is not None and hot.writer is writer:
                self._hot = None
            # Deferred frames must not survive into a reconnected writer
            # (replaying a stale PushTask double-executes the task).
            self.discard_deferred(writer=writer)
            err = version_err or RpcConnectionError(
                f"connection to {self.address} lost")
            for msg_id, fut in list(self._pending.items()):
                if fut._art_writer is not writer:
                    continue
                self._pending.pop(msg_id, None)
                if not fut.done():
                    fut.set_exception(err)

    def _on_hot_acks(self, body) -> None:
        """Resolve every future whose reply rode the coalesced ack
        frame (one frame, N acks).  Raises :class:`HotFrameError` on an
        undecodable frame — the read loop treats that as fatal to the
        connection (the batched replies behind the corruption are
        unrecoverable)."""
        acks = hotframe.decode_acks(body)
        for msg_id, value, is_err in acks:
            fut = self._pending.get(msg_id)
            if fut is None or fut.done():
                continue
            if is_err:
                fut.set_exception(
                    value if isinstance(value, BaseException)
                    else RpcError(str(value)))
            else:
                fut.set_result(value)

    def _encode_hot_call(self, hot: _HotSendState, spec: TaskSpec,
                         msg_id: int) -> bytes | None:
        """Hot-wire encoding of one PushTask, or None when the spec is
        not hot-eligible / the template cache is full (the caller falls
        back to the pickled frame).  A first-use template rides framed
        IMMEDIATELY ahead of its call in the same write, so it can
        never arrive late."""
        key = hotframe.template_key(spec)
        if key is None:
            hotframe.counters["fallback_ineligible"] += 1
            return None
        tid, is_new = hot.templates.intern(key)
        if tid is None:
            # Distinct from ineligible: the fix for THIS fallback is
            # raising the cache bound, not reshaping specs.
            hotframe.counters["fallback_cache_full"] += 1
            return None
        call = _encode_hot_frame(hotframe.encode_call(tid, spec, msg_id))
        if is_new:
            return _encode_hot_frame(hotframe.encode_template(tid, spec)) \
                + call
        return call

    def _pop_pending(self, fut) -> None:
        # Cleanup on any terminal state — including cancellation by a
        # wait_for timeout — so abandoned calls never leak their entry.
        self._pending.pop(fut._art_msg_id, None)

    def _register_pending(self) -> tuple[int, asyncio.Future]:
        msg_id = next(self._counter)
        fut = self._io.loop.create_future()
        fut._art_msg_id = msg_id
        # The connection this call belongs to (both registration sites
        # run unsuspended after the writer check / _ensure_connected):
        # teardown fails only its own connection's futures with it.
        fut._art_writer = self._writer
        self._pending[msg_id] = fut
        fut.add_done_callback(self._pop_pending_cb)
        return msg_id, fut

    def _encode_request(self, method: str, payload: Any,
                        msg_id: int) -> tuple[bytes, Any]:
        """(frame bytes, writer-tag) for one request — the ONE place
        that decides hot vs pickled encoding, shared by the sync and
        async send paths so they cannot desynchronize.  The tag is the
        connection a hot frame was encoded for (None for pickled)."""
        t0 = time.perf_counter_ns()
        if method == "PushTask" and type(payload) is TaskSpec:
            hot = self._hot
            if hot is not None and hot.writer is self._writer:
                frame = self._encode_hot_call(hot, payload, msg_id)
                if frame is not None:
                    _wire_account(method, "send", len(frame),
                                  time.perf_counter_ns() - t0,
                                  self.wire_stats)
                    return frame, hot.writer
        frame = _encode_frame((_REQ, msg_id, method, payload))
        _wire_account(method, "send", len(frame),
                      time.perf_counter_ns() - t0, self.wire_stats)
        return frame, None

    def try_send_deferred(self, method: str, payload: Any):
        """Sync defer-enqueue fast path (io-loop only): on an
        established, chaos-free connection this is the whole per-call
        send — no coroutine, no awaits.  Returns the reply future, or
        None when the slow path must run (not connected, or chaos
        injection is configured — the async path owns those)."""
        if self._chaos_active:
            return None
        writer = self._writer
        if writer is None or writer.is_closing():
            return None
        msg_id, fut = self._register_pending()
        frame, tag = self._encode_request(method, payload, msg_id)
        self._outbox.append((frame, fut, tag))
        return fut

    async def send_request(self, method: str, payload: Any = None,
                           defer: bool = False) -> asyncio.Future:
        """Write the request frame now; return the future for the reply.

        Callers needing strict send ordering (e.g. per-actor task queues)
        await this sequentially and await the reply futures separately, so
        ordering and pipelining compose.

        ``defer=True`` queues the frame in the client outbox instead of
        writing; a later :meth:`flush_deferred` ships every queued frame
        in one transport write (one syscall for a pipeline burst).
        """
        if self._chaos.should_fail(method):
            raise RpcConnectionError(f"[chaos] injected failure for {method}")
        delay = self._chaos.delay_for(method)
        if delay > 0:
            await asyncio.sleep(delay)
        await self._ensure_connected()
        msg_id, fut = self._register_pending()
        frame, writer_tag = self._encode_request(method, payload, msg_id)
        if defer:
            self._outbox.append((frame, fut, writer_tag))
            return fut
        await self._write_frame(frame)
        return fut

    async def _write_frame(self, frame: bytes):
        """Write with flow control: the common case (transport buffer
        under the threshold) is a plain non-awaiting write; only a
        backed-up transport pays the drain await (and its lock)."""
        writer = self._writer
        if writer is None:
            raise RpcConnectionError(f"connection to {self.address} lost")
        writer.write(frame)
        if writer.transport.get_write_buffer_size() > _DRAIN_THRESHOLD:
            async with self._write_lock:
                writer = self._writer
                if writer is None:
                    raise RpcConnectionError(
                        f"connection to {self.address} lost")
                await writer.drain()

    async def flush_deferred(self):
        """Ship all defer-queued frames in a single transport write.

        Hot frames that were encoded for a connection that has since
        turned over are failed instead of shipped — their template ids
        mean nothing to the new peer (the caller's retry path re-pushes
        them, re-encoded against the fresh connection)."""
        if not self._outbox:
            return
        entries, self._outbox = self._outbox, []
        writer = self._writer
        stale = [e for e in entries
                 if e[2] is not None and e[2] is not writer]
        if stale:
            self._fail_entries(stale)
            entries = [e for e in entries
                       if e[2] is None or e[2] is writer]
            if not entries:
                return
        try:
            await self._write_frame(entries[0][0] if len(entries) == 1
                                    else b"".join(f for f, _, _ in entries))
        except BaseException:
            self._fail_entries(entries)
            raise

    def discard_deferred(self, writer=None):
        """Drop never-shipped deferred frames, failing their futures —
        replaying them on a later (re)connection would double-execute
        tasks that the caller already rerouted elsewhere.  With
        ``writer``, only entries registered against that connection are
        dropped: a stale read loop racing a completed reconnect must
        not fail the new connection's deferred traffic."""
        if writer is None:
            entries, self._outbox = self._outbox, []
        else:
            entries = [e for e in self._outbox
                       if e[1]._art_writer is writer]
            if entries:
                self._outbox = [e for e in self._outbox
                                if e[1]._art_writer is not writer]
        self._fail_entries(entries)

    def _fail_entries(self, entries):
        err = RpcConnectionError(
            f"request to {self.address} was never sent")
        for _frame, fut, _tag in entries:
            if not fut.done():
                fut.set_exception(err)

    async def call_async(
        self, method: str, payload: Any = None, timeout: float | None = None
    ) -> Any:
        # Tracing fast path: one contextvar read.  Calls made inside a
        # sampled trace (the caller's context rides into this coroutine
        # via the event-loop context copy) record a client span with a
        # serialize/wire stage split; everything else takes the bare
        # path below untouched.
        ctx = _trace_current()
        if ctx is not None:
            return await self._traced_call(ctx, method, payload, timeout)
        fut = await self.send_request(method, payload)
        return await self._await_reply(fut, method, timeout)

    async def _await_reply(self, fut, method: str,
                           timeout: float | None) -> Any:
        """ONE deadline semantic for traced and untraced calls:
        ``timeout <= 0`` is the explicit no-deadline escape hatch
        (long-running task pushes); None takes the config default."""
        if timeout is None:
            timeout = global_config().rpc_call_timeout_s
        if timeout <= 0:
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError as e:
            raise RpcTimeoutError(
                f"{method} to {self.address} timed out") from e

    async def _traced_call(self, ctx, method: str, payload: Any,
                           timeout: float | None) -> Any:
        """call_async under a sampled trace context: record an
        ``rpc:{method}`` client span (stages: serialize = encode+write,
        wire = flight + server time) and feed the
        ``art_rpc_latency_s{method,stage}`` histogram with the trace id
        as its exemplar."""
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        t_wall = time.time()
        t0 = time.perf_counter()
        t_sent = t0
        err = False
        try:
            fut = await self.send_request(method, payload)
            t_sent = time.perf_counter()
            return await self._await_reply(fut, method, timeout)
        except BaseException:
            err = True
            raise
        finally:
            t_end = time.perf_counter()
            stages = {"serialize": t_sent - t0, "wire": t_end - t_sent}
            sid = tracing_plane.record_span(
                ctx, f"rpc:{method}", ts=t_wall, dur_s=t_end - t0,
                stages=stages,
                attrs={"method": method, "peer": self.address},
                error=err, service="rpc-client")
            if sid is not None:
                tracing_plane.record_rpc(method, stages, ctx.trace_id)

    async def oneway_async(self, method: str, payload: Any = None) -> None:
        await self._ensure_connected()
        t0 = time.perf_counter_ns()
        frame = _encode_frame((_ONEWAY, -1, method, payload))
        _wire_account(method, "send", len(frame),
                      time.perf_counter_ns() - t0, self.wire_stats)
        await self._write_frame(frame)

    async def oneway_many(self, items) -> None:
        """Ship a batch of ``(method, payload)`` oneways in one
        transport write (the coalesced refcount/publish path: a burst
        of per-call notifications costs one syscall, not N)."""
        await self._ensure_connected()
        frames = []
        for method, payload in items:
            t0 = time.perf_counter_ns()
            frame = _encode_frame((_ONEWAY, -1, method, payload))
            _wire_account(method, "send", len(frame),
                          time.perf_counter_ns() - t0, self.wire_stats)
            frames.append(frame)
        await self._write_frame(b"".join(frames))

    def call(self, method: str, payload: Any = None,
             timeout: float | None = None, retries: int = 0) -> Any:
        """Blocking call from any non-io thread, with connection retries."""
        from ant_ray_tpu._lint.lockcheck import note_blocking  # noqa: PLC0415

        # Runtime evidence for the static blocking-under-lock rule: if
        # the calling thread holds an instrumented lock across this
        # round trip, lockcheck reports the hold with its stack.
        note_blocking(f"RpcClient.call:{method}")
        attempt = 0
        while True:
            try:
                return self._io.run_coro(
                    self.call_async(method, payload, timeout)
                )
            except RpcConnectionError:
                attempt += 1
                if attempt > retries:
                    raise
                time.sleep(min(0.1 * 2 ** attempt, 2.0))

    def close(self):
        self._closed = True
        writer = self._writer
        if writer is not None:
            self._io.call_soon(writer.close)
            self._writer = None


class ClientPool:
    """Shared RpcClients keyed by address (ref: rpc client pools)."""

    def __init__(self):
        self._clients: dict[str, RpcClient] = {}
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._lock = make_lock("rpc.client_pool")

    def get(self, address: str) -> RpcClient:
        with self._lock:
            client = self._clients.get(address)
            if client is None or client._closed:
                if "," in address:
                    # A comma-joined replica list is a GCS HA spec: the
                    # pool hands back a leader-aware router with the
                    # RpcClient call surface, so every existing
                    # ``pool.get(gcs_address)`` call site gains
                    # redirect-following + re-resolve failover without
                    # changing.  (Import here: gcs_client imports this
                    # module.)
                    from ant_ray_tpu._private.gcs_client import (  # noqa: PLC0415
                        GcsRouter,
                    )

                    client = GcsRouter(address, self)
                else:
                    client = RpcClient(address)
                self._clients[address] = client
            return client

    def invalidate(self, address: str) -> None:
        with self._lock:
            client = self._clients.pop(address, None)
        if client is not None:
            client.close()

    def close_all(self):
        with self._lock:
            clients, self._clients = list(self._clients.values()), {}
        for c in clients:
            c.close()


def find_free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]
