"""Serialization: pickle-5 with out-of-band buffers + cloudpickle for code.

Equivalent role to the reference's serialization layer
(ref: python/ray/_private/serialization.py + the cloudpickle fork): data moves
zero-copy where possible (numpy / jax host buffers become out-of-band
PickleBuffers backed by shared memory on the receive side), functions and
actor classes go through cloudpickle, and ObjectRefs found inside values are
recorded so the ownership layer can track borrows.

jax.Array values are device-fetched to host on serialize and tagged so the
deserializer can rebuild them with ``jax.device_put`` (round 1: host path;
the HBM-resident object tier lives in device_store.py).
"""

from __future__ import annotations

import io
import os
import pickle
import sys
import threading
import types
from dataclasses import dataclass
from typing import Any, Callable

import cloudpickle

# Lazy jax import: control-plane processes must not pay jax startup.
_jax = None


def _maybe_jax():
    global _jax
    if _jax is None:
        try:
            from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

            _jax = import_jax()
        except ImportError:  # pragma: no cover
            _jax = False
    return _jax or None


def _jax_if_loaded():
    """jax, ONLY if this process already imported it: a value can be a
    jax Array only when jax is loaded, so the SERIALIZE-side probe must
    not pull the ~1s jax import onto a reply path — a serve replica's
    first error reply (e.g. an admission shed that must return in
    milliseconds) would otherwise eat the whole import."""
    if _jax is None and "jax" not in sys.modules:
        return None
    return _maybe_jax()


@dataclass
class SerializedObject:
    """A serialized value: a metadata pickle stream + raw buffers."""

    inband: bytes          # pickle-5 stream (buffers externalized)
    buffers: list[bytes | memoryview]
    contained_refs: list   # ObjectRefs found inside the value
    _header: bytes | None = None

    def total_bytes(self) -> int:
        return len(self.inband) + sum(len(b) for b in self.buffers)

    def _header_bytes(self) -> bytes:
        if self._header is None:
            self._header = pickle.dumps(
                (len(self.inband),
                 [memoryview(b).nbytes for b in self.buffers]),
                protocol=5)
        return self._header

    def payload_nbytes(self) -> int:
        """Exact wire size, without materializing the payload — lets the
        put path reserve an arena window and write straight into shared
        memory (one copy end-to-end instead of concat + copy)."""
        return (4 + len(self._header_bytes()) + len(self.inband)
                + sum(memoryview(b).nbytes for b in self.buffers))

    def to_payload(self) -> bytes:
        """Flatten to one contiguous byte string (header + inband + buffers)."""
        out = io.BytesIO()
        self._write_parts(out.write)
        return out.getvalue()

    def write_into(self, view: memoryview) -> None:
        """Write the payload directly into a writable buffer (an arena
        write grant) — the zero-intermediate-copy produce path."""
        pos = 0

        def sink(part):
            nonlocal pos
            n = memoryview(part).nbytes
            view[pos:pos + n] = part
            pos += n

        self._write_parts(sink)

    def _write_parts(self, write) -> None:
        header = self._header_bytes()
        write(len(header).to_bytes(4, "big"))
        write(header)
        write(self.inband)
        for b in self.buffers:
            write(b)

    @classmethod
    def from_payload(cls, payload: bytes | memoryview,
                     pin_owner=None) -> "SerializedObject":
        """Parse the wire form zero-copy: inband and buffers are
        memoryview slices of ``payload``.  When ``pin_owner`` is given
        (a zero-copy get from a pinned arena slot), each buffer slice is
        wrapped so deserialized arrays keep the pin alive for as long as
        they reference the shared memory (see _PinnedSlice)."""
        payload = memoryview(payload)
        hlen = int.from_bytes(payload[:4], "big")
        inband_len, buf_lens = pickle.loads(payload[4:4 + hlen])
        off = 4 + hlen
        inband = payload[off:off + inband_len]
        off += inband_len
        buffers = []
        for blen in buf_lens:
            mv = payload[off:off + blen]
            buffers.append(mv if pin_owner is None
                           else _pin_buffer(mv, pin_owner))
            off += blen
        return cls(inband=inband, buffers=buffers, contained_refs=[])


def _pin_buffer(mv: memoryview, owner):
    """A read-only buffer over ``mv`` whose consumers keep ``owner``
    (the client-side arena pin) alive: a numpy array deserialized
    zero-copy keeps it as its base, deferring the daemon-side ReadDone
    until the array is garbage collected — so the store can never
    recycle the slot under live readers (ref: plasma-backed read-only
    arrays).  Prefers the C-level art_native.PinnedBuffer (works on
    every CPython); falls back to the PEP 688 ``__buffer__`` wrapper on
    3.12+, and to a safe copy-out where neither is available (CPython
    < 3.12 can't export the buffer protocol from pure Python)."""
    from ant_ray_tpu._private.native import load_native  # noqa: PLC0415

    native = load_native()
    if native is not None:
        return native.PinnedBuffer(mv.toreadonly(), owner)
    if sys.version_info >= (3, 12):
        return _PinnedSlice(mv, owner)
    return bytes(mv)


class _PinnedSlice:
    """Pure-Python fallback for _pin_buffer (PEP 688 ``__buffer__``,
    honored by CPython 3.12+ only — see _pin_buffer for the dispatch)."""

    __slots__ = ("_mv", "_owner")

    def __init__(self, mv: memoryview, owner):
        self._mv = mv.toreadonly()
        self._owner = owner

    def __buffer__(self, flags):
        return self._mv

    def __len__(self):
        return self._mv.nbytes


_thread_local = threading.local()

_by_value_modules: set[str] = set()
_installed_top_levels: set[str] | None = None


def _is_installed_distribution(top_level: str) -> bool:
    """True if ``top_level`` belongs to any installed distribution
    (covers editable installs, whose __file__ points at the checkout)."""
    global _installed_top_levels
    if _installed_top_levels is None:
        try:
            from importlib import metadata  # noqa: PLC0415

            _installed_top_levels = set(metadata.packages_distributions())
        except Exception:  # noqa: BLE001 — no metadata, assume script
            _installed_top_levels = set()
    return top_level in _installed_top_levels


def _register_driver_module_by_value(obj: Any) -> None:
    """Ship driver-script code by value.

    cloudpickle pickles module-level functions/classes by reference,
    which breaks when the worker can't import the driver's module (a
    test file, a user script run from a checkout).  The reference's
    cloudpickle fork pickles driver code by value unconditionally; here
    we register any module that isn't installed (not under
    site-/dist-packages, not stdlib, not ant_ray_tpu itself) for
    by-value pickling, so classes and functions defined in driver
    scripts serialize self-contained.
    """
    module_name = getattr(obj, "__module__", None)
    if not module_name or module_name in _by_value_modules:
        return
    top = module_name.split(".")[0]
    if top in ("ant_ray_tpu", "__main__", "builtins") or \
            top in sys.stdlib_module_names:
        return  # __main__ is already by-value in cloudpickle
    module = sys.modules.get(module_name)
    file = getattr(module, "__file__", None)
    if module is None or not file:
        return
    norm = file.replace(os.sep, "/")
    if "site-packages" in norm or "dist-packages" in norm:
        return
    if _is_installed_distribution(top):
        # pip install -e / conda source checkouts: importable on workers
        # under their own name — shipping by value would fork the class
        # identity (worker-side isinstance against its own import fails).
        return
    try:
        cloudpickle.register_pickle_by_value(module)
        _by_value_modules.add(module_name)
    except Exception:  # noqa: BLE001 — fall back to by-reference
        pass


# bytes/bytearray above this size are shipped out-of-band (zero-copy on
# the serialize side) instead of being copied into the pickle stream.
_OOB_BYTES_THRESHOLD = 64 * 1024


class _ValuePickler(cloudpickle.Pickler):
    """Hot-path pickler (module-level: defining a class per serialize()
    call costs ~20µs, visible at 10k calls/s)."""

    def reducer_override(self, obj):
        t = type(obj)
        if t is bytes or t is bytearray:
            # Large raw byte blobs go out-of-band: the pickle stream
            # carries only a NEXT_BUFFER marker, buffer_callback gets a
            # zero-copy view of the original object.
            if len(obj) > _OOB_BYTES_THRESHOLD:
                return (t, (pickle.PickleBuffer(obj),))
            return NotImplemented
        jax = _jax_if_loaded()
        if jax is not None and isinstance(obj, jax.Array):
            import numpy as np  # noqa: PLC0415

            # Reduce to the host numpy array and let the pickle-5
            # machinery externalize its buffer in stream order — a
            # separate index-based buffer table would corrupt the
            # NEXT_BUFFER consumption order of other buffers.
            host = np.asarray(jax.device_get(obj))
            return (_rebuild_jax_array, (host,))
        if isinstance(obj, (type, types.FunctionType)):
            _register_driver_module_by_value(obj)
        # Defer to cloudpickle's own reducer_override (it implements
        # local-function/class support there, not in dispatch).
        return super().reducer_override(obj)


# Fast-path eligibility: exact scalar types (subclasses may carry
# reducers), short strings/bytes (large ones benefit from out-of-band
# buffer externalization), and shallow small containers of the same.
# These values cannot contain ObjectRefs, jax arrays, or anything else
# the custom pickler handles — plain pickle.dumps is byte-compatible
# with what the full pickler would emit and an order of magnitude
# cheaper (no pickler construction, no reducer dispatch, no BytesIO).
_SIMPLE_TYPES = frozenset({int, float, bool, type(None)})
_SIMPLE_SIZED = frozenset({str, bytes})
_SIMPLE_MAX_SIZED = 4096
_SIMPLE_MAX_ITEMS = 8


def _is_simple(value: Any, depth: int = 2) -> bool:
    t = type(value)
    if t in _SIMPLE_TYPES:
        return True
    if t in _SIMPLE_SIZED:
        return len(value) <= _SIMPLE_MAX_SIZED
    if depth:
        if t is tuple or t is list:
            return (len(value) <= _SIMPLE_MAX_ITEMS
                    and all(_is_simple(v, depth - 1) for v in value))
        if t is dict:
            return (len(value) <= _SIMPLE_MAX_ITEMS
                    and all(type(k) is str and _is_simple(v, depth - 1)
                            for k, v in value.items()))
    return False


def serialize(value: Any) -> SerializedObject:
    # Scalar fast path: the overwhelmingly common actor-call reply /
    # small-args shape on the control-plane hot path.
    if _is_simple(value):
        return SerializedObject(
            inband=pickle.dumps(value, protocol=5), buffers=[],
            contained_refs=[])
    buffers: list = []
    contained_refs: list = []

    # Track refs discovered by ObjectRef.__reduce__ during pickling.
    prev = getattr(_thread_local, "ref_sink", None)
    _thread_local.ref_sink = contained_refs

    def buffer_callback(pb: pickle.PickleBuffer) -> bool:
        buffers.append(pb.raw())
        return False  # externalize

    out = io.BytesIO()
    try:
        pickler = _ValuePickler(out, protocol=5,
                                buffer_callback=buffer_callback)
        pickler.dump(value)
    finally:
        _thread_local.ref_sink = prev
    return SerializedObject(
        inband=out.getvalue(), buffers=buffers, contained_refs=contained_refs
    )


def _rebuild_jax_array(host):
    jax = _maybe_jax()
    if jax is None:  # pragma: no cover
        return host
    import jax.numpy as jnp  # noqa: PLC0415

    return jnp.asarray(host)


def deserialize(obj: SerializedObject) -> Any:
    buffers = [memoryview(b) for b in obj.buffers]
    return pickle.loads(obj.inband, buffers=iter(buffers))


def record_contained_ref(ref) -> None:
    """Called from ObjectRef.__reduce__ while a serialize() is in flight."""
    sink = getattr(_thread_local, "ref_sink", None)
    if sink is not None:
        sink.append(ref)


def dumps_code(obj: Any) -> bytes:
    """Serialize a function/class definition (cloudpickle)."""
    _register_driver_module_by_value(obj)
    return cloudpickle.dumps(obj)


def loads_code(data: bytes) -> Any:
    return cloudpickle.loads(data)


def serialize_error(exc: BaseException) -> SerializedObject:
    try:
        return serialize(exc)
    except Exception:
        # Unpicklable exception: degrade to a plain TaskError-style message.
        from ant_ray_tpu.exceptions import TaskError  # noqa: PLC0415

        return serialize(TaskError("<unknown>", None, repr(exc)))
