"""Pluggable GCS table persistence.

Role of the reference's store clients (ref:
src/ray/gcs/store_client/redis_store_client.h, in_memory_store_client.h):
every GCS table write-throughs to a store client so a restarted head
reloads the cluster instead of electing a leader of nothing.  Redesigned
for this stack: the durable backend is a single sqlite file in the
session dir (no external Redis dependency; WAL mode keeps the write path
on the event loop sub-millisecond), keyed (table, key) → pickled record.
The HA leader selector points standby heads at the same file.
"""

from __future__ import annotations

import os
import sqlite3
import threading


class StoreClient:
    """Interface: byte-valued tables keyed by string."""

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> bytes | None:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def load_table(self, table: str) -> dict[str, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    """Process-local storage — the no-persistence default."""

    def __init__(self):
        self._tables: dict[str, dict[str, bytes]] = {}

    def put(self, table, key, value):
        self._tables.setdefault(table, {})[key] = value

    def get(self, table, key):
        return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        self._tables.get(table, {}).pop(key, None)

    def load_table(self, table):
        return dict(self._tables.get(table, {}))


class SqliteStoreClient(StoreClient):
    """Durable storage in one sqlite file (WAL journal).

    sqlite connections are not thread-safe by default; the GCS only
    touches the store from its IO loop, but a lock keeps misuse safe.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS art_store ("
                "  tbl TEXT NOT NULL, key TEXT NOT NULL, value BLOB,"
                "  PRIMARY KEY (tbl, key))")
            self._conn.commit()

    def put(self, table, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO art_store (tbl, key, value) "
                "VALUES (?, ?, ?)", (table, key, value))
            self._conn.commit()

    def get(self, table, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM art_store WHERE tbl = ? AND key = ?",
                (table, key)).fetchone()
        return row[0] if row else None

    def delete(self, table, key):
        with self._lock:
            self._conn.execute(
                "DELETE FROM art_store WHERE tbl = ? AND key = ?",
                (table, key))
            self._conn.commit()

    def load_table(self, table):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM art_store WHERE tbl = ?",
                (table,)).fetchall()
        return {key: value for key, value in rows}

    def close(self):
        with self._lock:
            self._conn.close()
