"""Pluggable GCS table persistence.

Role of the reference's store clients (ref:
src/ray/gcs/store_client/redis_store_client.h, in_memory_store_client.h):
every GCS table write-throughs to a store client so a restarted head
reloads the cluster instead of electing a leader of nothing.  Redesigned
for this stack: the durable backend is a single sqlite file in the
session dir (no external Redis dependency; WAL mode keeps the write path
on the event loop sub-millisecond), keyed (table, key) → pickled record.
The HA leader selector points standby heads at the same file.
"""

from __future__ import annotations

import logging
import os
import sqlite3


class StoreFenceError(RuntimeError):
    """A remote-store read fence did not drain within its budget: every
    read behind it could be stale.  Raised instead of proceeding — a
    silently-stale read is exactly what follower reads (which build
    read-your-writes on this fence) must not inherit.  The budget is
    the ``store_fence_timeout_s`` config knob."""


class StoreClient:
    """Interface: byte-valued tables keyed by string."""

    def put(self, table: str, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, table: str, key: str) -> bytes | None:
        raise NotImplementedError

    def delete(self, table: str, key: str) -> None:
        raise NotImplementedError

    def load_table(self, table: str) -> dict[str, bytes]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class InMemoryStoreClient(StoreClient):
    """Process-local storage — the no-persistence default."""

    def __init__(self):
        self._tables: dict[str, dict[str, bytes]] = {}

    def put(self, table, key, value):
        self._tables.setdefault(table, {})[key] = value

    def get(self, table, key):
        return self._tables.get(table, {}).get(key)

    def delete(self, table, key):
        self._tables.get(table, {}).pop(key, None)

    def load_table(self, table):
        return dict(self._tables.get(table, {}))


class SqliteStoreClient(StoreClient):
    """Durable storage in one sqlite file (WAL journal).

    sqlite connections are not thread-safe by default; the GCS only
    touches the store from its IO loop, but a lock keeps misuse safe.
    """

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._lock = make_lock("store_client.sqlite")
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS art_store ("
                "  tbl TEXT NOT NULL, key TEXT NOT NULL, value BLOB,"
                "  PRIMARY KEY (tbl, key))")
            self._conn.commit()

    def put(self, table, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO art_store (tbl, key, value) "
                "VALUES (?, ?, ?)", (table, key, value))
            self._conn.commit()

    def get(self, table, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM art_store WHERE tbl = ? AND key = ?",
                (table, key)).fetchone()
        return row[0] if row else None

    def delete(self, table, key):
        with self._lock:
            self._conn.execute(
                "DELETE FROM art_store WHERE tbl = ? AND key = ?",
                (table, key))
            self._conn.commit()

    def load_table(self, table):
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM art_store WHERE tbl = ?",
                (table,)).fetchall()
        return {key: value for key, value in rows}

    def close(self):
        with self._lock:
            self._conn.close()


class RemoteStoreClient(StoreClient):
    """Store client over the RPC'd store service (store_server.py) —
    the shared-store HA backend: the head's tables live on another
    machine, so a standby head anywhere can restore them (ref:
    src/ray/gcs/store_client/redis_store_client.h).

    Address form: ``art-store://host:port`` (or bare ``host:port``).
    Calls are synchronous with small retries — table writes are on the
    GCS mutation path, where the reference accepts the same Redis RTT.
    """

    def __init__(self, address: str):
        import asyncio

        from ant_ray_tpu._private.protocol import ClientPool

        self._asyncio = asyncio
        self.address = address.removeprefix("art-store://")
        self._client = ClientPool().get(self.address)
        # Ordered async write-through: GCS table mutations happen ON
        # the io loop, where a blocking round trip would deadlock the
        # loop against itself.  A single drainer task sends the queue
        # in order, retrying each write until it lands — so the store
        # always holds a PREFIX of the mutation history even across
        # store-server blips (the reference's async Redis write-through
        # with callback retries, redis_store_client.h).
        self._writes: asyncio.Queue | None = None
        self._drainer = None
        self._closed = False

    async def _drain_writes(self):
        while True:
            item = await self._writes.get()
            if item is None:
                return
            method, payload = item
            if method == "__fence__":
                # Read barrier: every write enqueued before this fence
                # has landed — release the waiting reader.
                payload.set_result(None)
                continue
            delay = 0.05
            while True:
                try:
                    await self._client.call_async(method, payload,
                                                  timeout=10)
                    break
                except Exception as e:  # noqa: BLE001 — store blip
                    if self._closed:
                        # close() gave up waiting: stop retrying into a
                        # store that will never take this write instead
                        # of spinning (and logging) forever.
                        return
                    logging.getLogger(__name__).warning(
                        "store write %s retrying: %s", method, e)
                    await self._asyncio.sleep(delay)
                    delay = min(delay * 2, 2.0)

    def _submit_write(self, method: str, payload: dict) -> None:
        loop = self._client._io.loop

        def _enqueue():
            if self._writes is None:
                self._writes = self._asyncio.Queue()
                self._drainer = self._asyncio.ensure_future(
                    self._drain_writes())
            self._writes.put_nowait((method, payload))

        loop.call_soon_threadsafe(_enqueue)

    def _read_fence(self, timeout: float | None = None) -> None:
        """Read-your-writes: block until every write this client
        enqueued so far has landed (a fence item through the ordered
        queue).  Without it a get() racing a queued delete/put reads
        the pre-write value.  A fence that does not drain within the
        budget (``store_fence_timeout_s`` by default) raises a typed
        :class:`StoreFenceError` — proceeding would hand the caller
        possibly-stale state with no signal."""
        import concurrent.futures

        from ant_ray_tpu._private.config import global_config  # noqa: PLC0415

        if timeout is None:
            timeout = global_config().store_fence_timeout_s
        fence: concurrent.futures.Future = concurrent.futures.Future()
        loop = self._client._io.loop

        def _enqueue():
            # No queue yet = nothing was ever written; closed = the
            # drainer is gone (close() flushed everything it will).
            # Otherwise the fence must ride the queue even when it
            # looks empty — the drainer pops an item *before* sending
            # it, so emptiness does not mean the last write landed.
            if self._writes is None or self._closed:
                fence.set_result(None)
                return
            self._writes.put_nowait(("__fence__", fence))

        loop.call_soon_threadsafe(_enqueue)
        try:
            fence.result(timeout)
        except concurrent.futures.TimeoutError:
            raise StoreFenceError(
                f"store read fence did not drain within {timeout:.0f}s "
                f"(store {self.address} unreachable or write backlog); "
                "refusing a possibly-stale read") from None

    def put(self, table, key, value):
        self._submit_write("StorePut", {"table": table, "key": key,
                                        "value": value})

    def get(self, table, key):
        self._read_fence()
        return self._client.call("StoreGet",
                                 {"table": table, "key": key}, retries=3)

    def delete(self, table, key):
        self._submit_write("StoreDelete",
                           {"table": table, "key": key})

    def load_table(self, table):
        self._read_fence()
        return self._client.call("StoreLoadTable", {"table": table},
                                 retries=3)

    def close(self):
        """Drain queued writes (bounded) so an orderly head shutdown
        leaves the store holding everything it acknowledged."""
        import concurrent.futures

        loop = self._client._io.loop
        self._closed = True

        async def _flush():
            if self._writes is None:
                return
            self._writes.put_nowait(None)
            await self._drainer
            # Resolve any fence that raced in behind the shutdown
            # sentinel so late readers don't stall out their timeout.
            while not self._writes.empty():
                method, payload = self._writes.get_nowait()
                if method == "__fence__" and not payload.done():
                    payload.set_result(None)

        try:
            self._asyncio.run_coroutine_threadsafe(
                _flush(), loop).result(timeout=5)
        except (concurrent.futures.TimeoutError, Exception):  # noqa: BLE001
            pass


def store_client_for(spec: str | None) -> StoreClient:
    """Resolve a store spec: None -> in-memory, ``art-store://...`` ->
    remote service, anything else -> local sqlite path."""
    if not spec:
        return InMemoryStoreClient()
    if spec.startswith("art-store://"):
        return RemoteStoreClient(spec)
    return SqliteStoreClient(spec)
