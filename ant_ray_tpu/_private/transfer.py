"""Bulk object-transfer data channel.

The control plane (asyncio RPC, protocol.py) is built for many small
messages; pushing multi-MiB transfer chunks through it costs an event
loop wakeup per ~128 KiB of socket buffer on both ends, which caps
node-to-node object bandwidth at a fraction of what the wire (or
loopback) can do.  The reference keeps its object plane on a dedicated
C++ gRPC data path for the same reason (ref: src/ray/object_manager/
object_manager.h — ObjectManager owns its own transfer service,
separate from the raylet's control RPCs).

This module is that data path, redesigned for the plane here:

* **holder side** — ``BulkServer``: one listener thread per node
  daemon; each puller connection gets a handler thread that serves
  ``(object_id, offset, length)`` requests straight from the arena —
  the payload is pinned, then ``sendall``-ed from the arena view, so a
  served chunk never materializes an intermediate ``bytes`` (except
  through the broadcast chunk cache, whose entries are stable copies
  shared across pullers).
* **puller side** — ``pull_chunks``: a blocking-socket worker that
  pipelines up to ``window`` requests ahead on one connection and
  ``recv_into``-s each reply *directly into the arena grant's
  memoryview* — socket → shared memory, no intermediate buffer, no
  event loop on the hot path.  The node daemon runs one worker per
  holder (stripes) via ``run_in_executor``.

Wire format (little protocol, version-fenced by the HELLO byte):

    connect:  client sends  b"ABK1"
    request:  u8 oid_len | oid bytes | u64 offset | u32 length | u8 flags
    reply:    u32 status_or_length | payload
              status 0xFFFFFFFF = object gone (stale holder)

Flags bit 0 marks a striped pull (stats only).
"""

from __future__ import annotations

import itertools
import logging
import socket
import struct
import threading
import time

from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.ids import ObjectID

logger = logging.getLogger(__name__)

MAGIC = b"ABK1"
_REQ_HEAD = struct.Struct(">B")            # oid_len
_REQ_BODY = struct.Struct(">QIB")          # offset, length, flags
_REPLY = struct.Struct(">I")               # length | MISS
MISS = 0xFFFFFFFF
FLAG_STRIPE = 1

_bulk_token_counter = itertools.count()


class BulkMiss(RuntimeError):
    """Holder no longer has the object (stale location)."""


def _recv_exactly(sock: socket.socket, view: memoryview) -> None:
    """Fill ``view`` from the socket (raises ConnectionError on EOF)."""
    pos = 0
    n = len(view)
    while pos < n:
        got = sock.recv_into(view[pos:], n - pos)
        if got == 0:
            raise ConnectionResetError("bulk peer closed mid-frame")
        pos += got


class BulkServer:
    """Holder-side bulk chunk server.  ``owner`` is the NodeManager —
    the server shares its object store, chunk cache, transfer counters
    and read log, so RPC-served and bulk-served chunks tally in one
    place."""

    def __init__(self, owner, host: str = "127.0.0.1"):
        self._owner = owner
        self._host = host
        self._sock: socket.socket | None = None
        self._stopping = False
        self.port = 0

    def start(self) -> int:
        self._sock = socket.socket()
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self._host, 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="art-bulk-accept").start()
        return self.port

    def stop(self) -> None:
        self._stopping = True
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stopping:
            sock = self._sock       # stop() nulls the attribute
            if sock is None:
                return
            try:
                conn, _addr = sock.accept()
            except OSError:
                return  # listener closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="art-bulk-serve").start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Bound every send/recv: a wedged peer must not hold a
            # served chunk's arena pin (or this thread) forever.
            conn.settimeout(120)
            hello = bytearray(len(MAGIC))
            _recv_exactly(conn, memoryview(hello))
            if bytes(hello) != MAGIC:
                return  # version fence: unknown peer, drop
            while not self._stopping:
                head = bytearray(1)
                _recv_exactly(conn, memoryview(head))
                oid_raw = bytearray(head[0])
                _recv_exactly(conn, memoryview(oid_raw))
                body = bytearray(_REQ_BODY.size)
                _recv_exactly(conn, memoryview(body))
                offset, length, flags = _REQ_BODY.unpack(bytes(body))
                self._serve_chunk(conn, ObjectID(bytes(oid_raw)),
                                  offset, length, flags)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_chunk(self, conn, object_id: ObjectID, offset: int,
                     length: int, flags: int) -> None:
        from ant_ray_tpu._lint.lockcheck import note_blocking  # noqa: PLC0415

        note_blocking("transfer.serve_chunk sendall")
        owner = self._owner
        owner._chunk_read_log.append((object_id.hex(), offset, length))
        delay = global_config().testing_chunk_serve_delay_s
        if delay > 0:
            time.sleep(delay)
        trunc = global_config().testing_chunk_truncate
        if trunc > 0 and length > trunc:
            # Chaos harness: a torn reply — declare and send fewer
            # bytes than the requested chunk.  The puller's length
            # check fails the pump, exercising stripe failover.
            view = owner.store.chunk_view_pinned(
                object_id, offset, trunc,
                token := ("bulk", next(_bulk_token_counter)))
            if view is None:
                conn.sendall(_REPLY.pack(MISS))
                return
            try:
                conn.sendall(_REPLY.pack(trunc))
                conn.sendall(view)
            finally:
                owner.store.unpin(object_id, token)
            return
        key = (object_id, offset, length)
        cached = owner.cache_get_chunk(key)
        if cached is not None:
            owner._bump_stats(chunk_cache_hits=1,
                              **({"stripe_cache_hits": 1}
                                 if flags & FLAG_STRIPE else {}))
            conn.sendall(_REPLY.pack(len(cached)))
            conn.sendall(cached)
            return
        token = ("bulk", next(_bulk_token_counter))
        view = owner.store.chunk_view_pinned(object_id, offset, length,
                                             token)
        if view is None:
            conn.sendall(_REPLY.pack(MISS))
            return
        try:
            owner._bump_stats(chunk_reads=1)
            owner.cache_put_chunk(key, view)
            # Zero-copy serve: arena → kernel.  The pin keeps the range
            # allocated even if the object is deleted mid-send (doomed
            # entries release on unpin).
            conn.sendall(_REPLY.pack(len(view)))
            conn.sendall(view)
        finally:
            owner.store.unpin(object_id, token)


def pull_chunks(address: tuple, object_id: ObjectID, size: int,
                chunk: int, window: int, take, requeue, write,
                striped: bool, progress: list | None = None,
                timeout_s: float = 60.0) -> int:
    """Blocking bulk-pull worker: drain chunk offsets from ``take()``
    over one pipelined connection, ``recv_into`` each straight into the
    grant via ``write(offset, length) -> memoryview``.  Returns the
    payload bytes successfully written; ``progress`` (a one-slot list
    written only by this worker) carries the same tally across the
    exception path, so bytes a dying holder already delivered still
    count (they are deliberately never re-pulled).

    Runs in an executor thread (never on the io loop).  On any failure
    every taken-but-incomplete offset is handed to ``requeue`` so a
    surviving holder can finish the stripe without re-pulling a byte —
    a taken offset is registered in ``inflight`` BEFORE its request is
    sent, so a failing send can never strand a chunk.
    """
    inflight: list[tuple[int, int]] = []   # (offset, length) issued
    pulled = 0
    from ant_ray_tpu._lint.lockcheck import note_blocking  # noqa: PLC0415

    note_blocking("transfer.pull_chunks socket I/O")
    sock = socket.create_connection(address, timeout=timeout_s)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(timeout_s)
        sock.sendall(MAGIC)
        flags = FLAG_STRIPE if striped else 0
        oid_raw = object_id.binary()
        req_tail = bytearray(_REQ_BODY.size)
        reply_head = bytearray(_REPLY.size)
        while True:
            while len(inflight) < max(1, window):
                off = take()
                if off is None:
                    break
                n = min(chunk, size - off)
                inflight.append((off, n))
                _REQ_BODY.pack_into(req_tail, 0, off, n, flags)
                sock.sendall(_REQ_HEAD.pack(len(oid_raw)) + oid_raw
                             + req_tail)
            if not inflight:
                return pulled
            off, n = inflight[0]
            _recv_exactly(sock, memoryview(reply_head))
            (got,) = _REPLY.unpack(bytes(reply_head))
            if got == MISS:
                raise BulkMiss(object_id.hex()[:12])
            if got != n:
                raise ConnectionResetError(
                    f"bulk holder replied {got} bytes for a {n}-byte "
                    f"chunk at {off}")
            _recv_exactly(sock, write(off, n))
            inflight.pop(0)
            pulled += n
            if progress is not None:
                progress[0] = pulled
    except BaseException:
        for off, _n in inflight:
            requeue(off)
        raise
    finally:
        try:
            sock.close()
        except OSError:
            pass
