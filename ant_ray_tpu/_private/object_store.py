"""Node-local shared-memory object store ("plasma"-equivalent).

Role of the reference's plasma store (ref: src/ray/object_manager/plasma/
store.h:55, obj_lifecycle_mgr.h, eviction_policy.h), redesigned: each object
is one file in a tmpfs session directory (/dev/shm on Linux), mmap'd by
readers for zero-copy access.  The node daemon owns the store; clients in
worker/driver processes open the files directly by path, so a local `get`
never copies through an RPC.  Pinning + LRU eviction of unpinned objects;
capacity enforcement with create-backpressure left to the node daemon.

Why files instead of multiprocessing.shared_memory: named SharedMemory
segments are entangled with the resource tracker (which unlinks segments
when their creating process exits); plain tmpfs files have exactly the
lifetime we manage, and POSIX keeps mappings valid after unlink so readers
holding an mmap survive eviction.
"""

from __future__ import annotations

import logging
import mmap
import os
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ant_ray_tpu._lint.lockcheck import make_lock, make_rlock
from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.ids import ObjectID
from ant_ray_tpu.exceptions import ObjectLostError

logger = logging.getLogger(__name__)


@dataclass
class ObjectEntry:
    object_id: ObjectID
    size: int
    # Read pins, addressed by caller-unique tokens (the daemon's pin-lease
    # tokens): token-addressing lets an unpin land on exactly the entry
    # generation it pinned, even after the id was deleted and re-created
    # (lineage reconstruction re-stores under the same id).
    pin_tokens: set = field(default_factory=set)
    sealed: bool = False
    offset: int | None = None       # arena payload offset (native mode)
    created_at: float = field(default_factory=time.monotonic)

    @property
    def pin_count(self) -> int:
        return len(self.pin_tokens)


ARENA_FILENAME = "arena.buf"


class ObjectStore:
    """Node-side store: tracks entries, capacity, pins, and LRU eviction.

    Two storage backends share the bookkeeping:
    * **arena** (preferred): one mmap'd tmpfs file managed by the C++
      boundary-tag allocator (native/store_core.cpp) — objects are
      [offset, size) windows, created by granting write buffers to
      colocated producers (plasma's create→seal protocol).
    * **file-per-object** fallback when the native extension is
      unavailable.
    """

    def __init__(self, directory: str, capacity_bytes: int,
                 use_arena: bool = True, on_delete=None,
                 spill_dir: str | None = None):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._capacity = capacity_bytes
        self._used = 0
        # Called (outside no lock guarantees — keep it cheap/thread-safe)
        # with each ObjectID removed by eviction or deletion, so the
        # daemon can retract the node's GCS location record.
        self._on_delete = on_delete
        # Spill-on-evict to disk (ref: LocalObjectManager,
        # local_object_manager.h:44): evicted sealed objects move to
        # spill_dir and restore transparently on next access, so the
        # node keeps serving them and no location retraction happens.
        self._spill_dir = spill_dir
        self._spilled: dict[ObjectID, int] = {}   # oid -> size
        if spill_dir:
            os.makedirs(spill_dir, exist_ok=True)
        self._entries: "OrderedDict[ObjectID, ObjectEntry]" = OrderedDict()
        # Deleted-while-pinned payloads: invisible to lookups, bytes kept
        # allocated until the last read pin drops (see _delete_locked).
        # A list, not a dict: the same object id can be doomed more than
        # once (delete → re-create → delete again, each under pins).
        self._doomed: list[ObjectEntry] = []
        self._lock = make_rlock("object_store.arena")
        self._arena = None
        if use_arena:
            from ant_ray_tpu._private.native import load_native  # noqa: PLC0415

            native = load_native()
            if native is not None:
                self._arena = native.Arena(
                    self.arena_path, capacity=capacity_bytes, create=True)

    @property
    def arena_path(self) -> str:
        return os.path.join(self._dir, ARENA_FILENAME)

    @property
    def uses_arena(self) -> bool:
        return self._arena is not None

    # ---- arena create/seal protocol (native mode)

    def create_buffer(self, object_id: ObjectID, size: int) -> int:
        """Reserve an unsealed write window; returns the payload offset.

        Raises BufferExistsError carrying whether the existing entry is
        sealed, so callers can distinguish idempotent re-put (sealed)
        from an abandoned grant (unsealed → abort and retry)."""
        with self._lock:
            existing = self._entries.get(object_id)
            if existing is not None:
                raise BufferExistsError(object_id, existing.sealed)
            self._ensure_space(size)
            offset = self._arena_alloc(size)
            self._entries[object_id] = ObjectEntry(
                object_id, size, sealed=False, offset=offset)
            self._used += size
            return offset

    def seal_buffer(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise ObjectLostError(object_id, "seal of unknown buffer")
            entry.sealed = True

    def abort_buffer(self, object_id: ObjectID) -> None:
        """Drop an unsealed grant (failed pull / crashed producer)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and not entry.sealed:
                self._delete_locked(object_id)

    def grant_age(self, object_id: ObjectID) -> float:
        """Seconds since an *unsealed* grant was created; +inf when the
        entry is missing or sealed.  Lets the daemon distinguish a live
        producer mid-write from a grant orphaned by a crash."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or entry.sealed:
                return float("inf")
            return time.monotonic() - entry.created_at

    def view_unsealed(self, object_id: ObjectID) -> memoryview:
        """Writable view of an unsealed arena grant (daemon-side sink for
        pulls; keeps _arena private to this class)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or entry.sealed or entry.offset is None:
                raise ObjectLostError(object_id, "no unsealed arena grant")
            return self._arena.view(entry.offset, entry.size)

    def _arena_alloc(self, size: int) -> int:
        while True:
            try:
                return self._arena.alloc(max(size, 1))
            except MemoryError:
                # Accounting says it fits but fragmentation bites: evict.
                if not self._evict_one():
                    raise ObjectStoreFullError(
                        "arena fragmented and nothing evictable") from None

    def arena_file_offset(self, payload_offset: int) -> int:
        """Absolute file offset for a payload offset (layout knowledge
        stays on the native side via the heap_start getter)."""
        return self._arena.heap_start + payload_offset

    def locate(self, object_id: ObjectID) -> dict | None:
        """{"path", "offset", "size"} for readers; offset is an absolute
        file offset (None = file-per-object fallback)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None and self._restore_locked(object_id):
                entry = self._entries.get(object_id)
            if entry is None or not entry.sealed:
                return None
            self._entries.move_to_end(object_id)
            if entry.offset is not None:
                return {"path": self.arena_path,
                        "offset": self.arena_file_offset(entry.offset),
                        "size": entry.size}
            return {"path": self.path_of(object_id), "offset": None,
                    "size": entry.size}

    # ---- paths

    def path_of(self, object_id: ObjectID) -> str:
        return os.path.join(self._dir, object_id.hex())

    @property
    def directory(self) -> str:
        return self._dir

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def used(self) -> int:
        return self._used

    @property
    def spilled_bytes(self) -> int:
        with self._lock:
            return self._spilled_bytes()

    # ---- write path

    def create(self, object_id: ObjectID, payload: bytes | memoryview) -> str:
        """Write + seal an object; returns the file path.

        Evicts unpinned LRU objects if needed; raises ObjectStoreFullError
        when the payload cannot fit even after eviction.
        """
        size = len(payload)
        with self._lock:
            if object_id in self._entries:
                return self.path_of(object_id)  # idempotent re-put
            self._ensure_space(size)
            if self._arena is not None:
                offset = self._arena_alloc(size)
                self._arena.view(offset, size)[:] = payload
                self._entries[object_id] = ObjectEntry(
                    object_id, size, sealed=True, offset=offset)
                self._used += size
                return self.arena_path
            path = self.path_of(object_id)
            with open(path, "wb") as f:
                f.write(payload)
            self._entries[object_id] = ObjectEntry(object_id, size, sealed=True)
            self._used += size
            return path

    def seal_file(self, object_id: ObjectID, tmp_path: str) -> str:
        """Adopt a fully-written temp file as a sealed object (producer
        fallback path; in arena mode the contents move into the arena)."""
        size = os.path.getsize(tmp_path)
        with self._lock:
            if object_id in self._entries:
                os.unlink(tmp_path)
                return self.path_of(object_id)
            self._ensure_space(size)
            if self._arena is not None:
                offset = self._arena_alloc(size)
                view = self._arena.view(offset, size)
                with open(tmp_path, "rb") as f:
                    f.readinto(view)
                os.unlink(tmp_path)
                self._entries[object_id] = ObjectEntry(
                    object_id, size, sealed=True, offset=offset)
                self._used += size
                return self.arena_path
            final = self.path_of(object_id)
            os.rename(tmp_path, final)
            self._entries[object_id] = ObjectEntry(object_id, size, sealed=True)
            self._used += size
            return final

    def _ensure_space(self, size: int) -> None:
        if size > self._capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity "
                f"{self._capacity}")
        while self._used + size > self._capacity:
            evicted = self._evict_one()
            if not evicted:
                raise ObjectStoreFullError(
                    f"store full ({self._used}/{self._capacity} bytes) and "
                    "all objects pinned")

    def _evict_one(self) -> bool:
        for oid, entry in self._entries.items():
            # Unsealed grants are producer-owned and never evictable —
            # freeing their slot while another process writes through its
            # view would corrupt whatever reuses the memory.
            if entry.pin_count == 0 and entry.sealed:
                if self._spill_dir is not None:
                    self._spill_locked(oid, entry)
                else:
                    self._delete_locked(oid)
                return True
        return False

    def _spill_path(self, object_id: ObjectID) -> str:
        return os.path.join(self._spill_dir, object_id.hex())

    def _spill_locked(self, object_id: ObjectID, entry: ObjectEntry):
        """Move a sealed object's payload to disk, then drop it from
        memory WITHOUT retracting its location (this node still serves
        it, via restore).

        The disk write happens under the store lock — synchronous-spill
        simplicity traded against the reference's async spill IO
        workers (local_object_manager.h:109); revisit if eviction of
        very large objects shows up on daemon latency."""
        if self._spilled_bytes() + entry.size > \
                global_config().max_spill_bytes:
            logger.warning("spill capacity exhausted; dropping %s",
                           object_id.hex()[:8])
            self._delete_locked(object_id)
            return
        path = self._spill_path(object_id)
        try:
            with open(path, "wb") as f:
                if entry.offset is not None:
                    f.write(self._arena.view(entry.offset, entry.size))
                else:
                    with open(self.path_of(object_id), "rb") as src:
                        f.write(src.read())
        except OSError as e:
            logger.warning("spill of %s failed (%s); dropping",
                           object_id.hex()[:8], e)
            self._delete_locked(object_id)
            return
        self._spilled[object_id] = entry.size
        self._delete_locked(object_id, notify=False)

    def _spilled_bytes(self) -> int:
        return sum(self._spilled.values())

    def _restore_locked(self, object_id: ObjectID) -> bool:
        """Bring a spilled object back into the store (ref:
        AsyncRestoreSpilledObject, local_object_manager.h:130).  The
        spill record survives a failed restore (e.g. store full of
        pinned entries) so a later access can retry."""
        size = self._spilled.get(object_id)
        if size is None:
            return False
        path = self._spill_path(object_id)
        try:
            with open(path, "rb") as f:
                payload = f.read()
        except FileNotFoundError:
            self._spilled.pop(object_id, None)
            return False
        try:
            self.create(object_id, payload)
        except ObjectStoreFullError:
            # The arena needs a CONTIGUOUS range: pinned entries can
            # fragment the free space so the alloc fails even though
            # accounting says the payload fits.  A spilled object must
            # not become unreadable while capacity exists — fall back
            # to a file-per-object entry (mmap'd by readers like any
            # file entry; no contiguous requirement).  Only a true
            # accounting shortfall (capacity consumed by pins) keeps
            # the record for a later retry.
            if self._used + size > self._capacity:
                return False           # record kept; retry later
            with open(self.path_of(object_id), "wb") as f:
                f.write(payload)
            self._entries[object_id] = ObjectEntry(
                object_id, size, sealed=True)
            self._used += size
        del self._spilled[object_id]
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        return True

    def _delete_locked(self, object_id: ObjectID,
                       notify: bool = True) -> None:
        entry = self._entries.pop(object_id, None)
        if entry is None:
            return
        if notify and self._on_delete is not None and entry.sealed:
            self._on_delete(object_id)
        if entry.pin_tokens and entry.offset is not None:
            # Live readers hold views into this arena payload (zero-copy
            # gets, in-flight transfer reads).  Tombstone: the entry is
            # gone for lookups (the location record above is retracted)
            # but the range stays allocated until the last unpin —
            # freeing now would let a new put recycle it under a live
            # read-only numpy view (ref: plasma defers deletion of
            # objects with nonzero client map counts).  File-backed
            # entries need no tombstone: POSIX keeps mmaps valid after
            # unlink, and unlinking immediately avoids clobbering the
            # file of a later re-create under the same id.
            self._doomed.append(entry)
            return
        self._free_payload_locked(entry)

    def _free_payload_locked(self, entry: ObjectEntry) -> None:
        self._used -= entry.size
        if entry.offset is not None:
            try:
                self._arena.free(entry.offset)
            except ValueError:
                pass
            return
        try:
            os.unlink(self.path_of(entry.object_id))
        except FileNotFoundError:
            pass

    # ---- read path

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return (object_id in self._entries
                    or object_id in self._spilled)

    def size_of(self, object_id: ObjectID) -> int | None:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry.size if entry else None

    def touch(self, object_id: ObjectID) -> None:
        """LRU bump."""
        with self._lock:
            if object_id in self._entries:
                self._entries.move_to_end(object_id)

    def pin(self, object_id: ObjectID, token) -> None:
        """Pin the current entry for ``object_id`` under a caller-unique
        ``token`` (the daemon's pin-lease token)."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise ObjectLostError(object_id, "pin on missing object")
            entry.pin_tokens.add(token)

    def unpin(self, object_id: ObjectID, token) -> None:
        """Drop the pin ``token``.  Token-addressed so an unpin after the
        id was deleted and re-created lands on the doomed generation the
        reader actually pinned — never on the new entry."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and token in entry.pin_tokens:
                entry.pin_tokens.discard(token)
                return
            for i, doomed in enumerate(self._doomed):
                if token in doomed.pin_tokens:
                    doomed.pin_tokens.discard(token)
                    if not doomed.pin_tokens:
                        del self._doomed[i]
                        self._free_payload_locked(doomed)
                    return

    def is_doomed(self, object_id: ObjectID) -> bool:
        """True while a deleted-but-still-pinned payload lingers
        (test/introspection hook)."""
        with self._lock:
            return any(d.object_id == object_id for d in self._doomed)

    def delete(self, object_id: ObjectID, notify: bool = True) -> None:
        """notify=False suppresses the on_delete hook — used for GCS-
        driven deletes, where the location record is already gone."""
        with self._lock:
            if self._spilled.pop(object_id, None) is not None:
                try:
                    os.unlink(self._spill_path(object_id))
                except FileNotFoundError:
                    pass
            self._delete_locked(object_id, notify=notify)

    def list_objects(self) -> list[ObjectID]:
        with self._lock:
            return list(self._entries)

    def object_stats(self) -> list[dict]:
        """Per-object residency detail for the memory-attribution join
        (``art memory`` / ``/api/memory``): every resident AND spilled
        object with its size, pin count, and storage tier.  One
        snapshot under the lock — readers get a consistent view."""
        now = time.monotonic()
        with self._lock:
            out = [
                {
                    "object_id": oid.hex(),
                    "size": entry.size,
                    "pins": entry.pin_count,
                    "sealed": entry.sealed,
                    "tier": ("arena" if entry.offset is not None
                             else "file"),
                    "created_age_s": now - entry.created_at,
                }
                for oid, entry in self._entries.items()
            ]
            out.extend(
                {
                    "object_id": oid.hex(),
                    "size": size,
                    "pins": 0,
                    "sealed": True,
                    "tier": "spilled",
                    "created_age_s": None,
                }
                for oid, size in self._spilled.items()
            )
        return out

    def chunk_view_pinned(self, object_id: ObjectID, offset: int,
                          length: int,
                          token) -> memoryview | bytes | None:
        """Serving-side chunk window for the bulk transfer channel:
        arena-backed objects are PINNED under ``token`` and a direct
        arena view is returned — the caller streams it to the socket
        and then calls :meth:`unpin` (the pin keeps the range allocated
        across a concurrent delete via the doomed list, so a mid-send
        eviction can never recycle the bytes under the socket).
        File-backed/spilled objects return a plain read (POSIX keeps
        the bytes stable without a pin).  ``None`` when the object is
        gone."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None and self._restore_locked(object_id):
                entry = self._entries.get(object_id)
            if entry is None or not entry.sealed:
                return None
            self._entries.move_to_end(object_id)
            if entry.offset is not None:
                if offset >= entry.size:
                    return b""
                end = min(offset + length, entry.size)
                entry.pin_tokens.add(token)
                return self._arena.view(entry.offset + offset,
                                        end - offset)
        try:
            with open(self.path_of(object_id), "rb") as f:
                f.seek(offset)
                return f.read(length)
        except FileNotFoundError:
            return None

    def read_chunk(self, object_id: ObjectID, offset: int, length: int) -> bytes:
        """Read a chunk for cross-node transfer."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None and self._restore_locked(object_id):
                entry = self._entries.get(object_id)
            if entry is None:
                raise ObjectLostError(object_id, "read on missing object")
            self._entries.move_to_end(object_id)
            if entry.offset is not None:
                end = min(offset + length, entry.size)
                if offset >= entry.size:
                    return b""
                return bytes(self._arena.view(
                    entry.offset + offset, end - offset))
        with open(self.path_of(object_id), "rb") as f:
            f.seek(offset)
            return f.read(length)

    def destroy(self) -> None:
        with self._lock:
            for oid in list(self._entries):
                self._delete_locked(oid)
            if self._arena is not None:
                # Do NOT munmap: in-flight daemon coroutines may still
                # hold raw views into the mapping (native views don't
                # refcount the arena).  Unlink the file and retire the
                # mapping instead — tmpfs space is reclaimed when the
                # last mapping dies at process exit, which is imminent.
                self._retired_arena = self._arena
                self._arena = None
                try:
                    os.unlink(self.arena_path)
                except FileNotFoundError:
                    pass
        try:
            os.rmdir(self._dir)
        except OSError:
            pass


class ObjectStoreFullError(ObjectLostError):
    def __init__(self, message: str):
        Exception.__init__(self, message)


class BufferExistsError(ValueError):
    def __init__(self, object_id: ObjectID, sealed: bool):
        super().__init__(f"buffer for {object_id.hex()[:12]} exists "
                         f"(sealed={sealed})")
        self.object_id = object_id
        self.sealed = sealed


class ArenaClient:
    """Client-side zero-copy windows into node arena files.  One shared
    mapping per arena path; windows are plain memoryview slices, so reads
    and producer writes never copy through an RPC."""

    def __init__(self):
        self._maps: dict[str, memoryview] = {}
        self._lock = make_lock("object_store.mmap_pool")

    def _mapping(self, path: str) -> memoryview:
        with self._lock:
            view = self._maps.get(path)
            if view is None:
                with open(path, "r+b") as f:
                    size = os.fstat(f.fileno()).st_size
                    m = mmap.mmap(f.fileno(), size)
                view = memoryview(m)
                self._maps[path] = view
            return view

    def view(self, path: str, offset: int, size: int) -> memoryview:
        """Window at an *absolute* file offset (the daemon converts from
        payload offsets; clients never know the arena layout)."""
        return self._mapping(path)[offset:offset + size]

    def close(self):
        with self._lock:
            self._maps.clear()


def open_object(path: str) -> memoryview:
    """Client-side zero-copy read: mmap the sealed object file.

    The returned memoryview keeps the mapping alive; deserialized arrays
    referencing it remain valid even if the store evicts (unlinks) the file.
    """
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size == 0:
            return memoryview(b"")
        mapping = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        return memoryview(mapping)


def default_store_capacity() -> int:
    """30% of system memory, capped by available tmpfs space."""
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page_size = os.sysconf("SC_PAGE_SIZE")
        total = pages * page_size
    except (ValueError, OSError):  # pragma: no cover
        total = 8 << 30
    cap = int(total * 0.3)
    try:
        stat = os.statvfs("/dev/shm")
        cap = min(cap, int(stat.f_bavail * stat.f_frsize * 0.8))
    except OSError:  # pragma: no cover
        pass
    return max(cap, 64 << 20)
