"""Node-local shared-memory object store ("plasma"-equivalent).

Role of the reference's plasma store (ref: src/ray/object_manager/plasma/
store.h:55, obj_lifecycle_mgr.h, eviction_policy.h), redesigned: each object
is one file in a tmpfs session directory (/dev/shm on Linux), mmap'd by
readers for zero-copy access.  The node daemon owns the store; clients in
worker/driver processes open the files directly by path, so a local `get`
never copies through an RPC.  Pinning + LRU eviction of unpinned objects;
capacity enforcement with create-backpressure left to the node daemon.

Why files instead of multiprocessing.shared_memory: named SharedMemory
segments are entangled with the resource tracker (which unlinks segments
when their creating process exits); plain tmpfs files have exactly the
lifetime we manage, and POSIX keeps mappings valid after unlink so readers
holding an mmap survive eviction.
"""

from __future__ import annotations

import logging
import mmap
import os
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field

from ant_ray_tpu._private.ids import ObjectID
from ant_ray_tpu.exceptions import ObjectLostError

logger = logging.getLogger(__name__)


@dataclass
class ObjectEntry:
    object_id: ObjectID
    size: int
    pin_count: int = 0
    sealed: bool = False
    created_at: float = field(default_factory=time.monotonic)


class ObjectStore:
    """Node-side store: tracks entries, capacity, pins, and LRU eviction."""

    def __init__(self, directory: str, capacity_bytes: int):
        self._dir = directory
        os.makedirs(directory, exist_ok=True)
        self._capacity = capacity_bytes
        self._used = 0
        self._entries: "OrderedDict[ObjectID, ObjectEntry]" = OrderedDict()
        self._lock = threading.RLock()

    # ---- paths

    def path_of(self, object_id: ObjectID) -> str:
        return os.path.join(self._dir, object_id.hex())

    @property
    def directory(self) -> str:
        return self._dir

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def used(self) -> int:
        return self._used

    # ---- write path

    def create(self, object_id: ObjectID, payload: bytes | memoryview) -> str:
        """Write + seal an object; returns the file path.

        Evicts unpinned LRU objects if needed; raises ObjectStoreFullError
        when the payload cannot fit even after eviction.
        """
        size = len(payload)
        with self._lock:
            if object_id in self._entries:
                return self.path_of(object_id)  # idempotent re-put
            self._ensure_space(size)
            path = self.path_of(object_id)
            with open(path, "wb") as f:
                f.write(payload)
            self._entries[object_id] = ObjectEntry(object_id, size, sealed=True)
            self._used += size
            return path

    def seal_file(self, object_id: ObjectID, tmp_path: str) -> str:
        """Adopt a fully-written temp file as a sealed object (zero-copy
        producer path: colocated workers write into the store directory and
        the daemon renames into place)."""
        size = os.path.getsize(tmp_path)
        with self._lock:
            if object_id in self._entries:
                os.unlink(tmp_path)
                return self.path_of(object_id)
            self._ensure_space(size)
            final = self.path_of(object_id)
            os.rename(tmp_path, final)
            self._entries[object_id] = ObjectEntry(object_id, size, sealed=True)
            self._used += size
            return final

    def _ensure_space(self, size: int) -> None:
        if size > self._capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds store capacity "
                f"{self._capacity}")
        while self._used + size > self._capacity:
            evicted = self._evict_one()
            if not evicted:
                raise ObjectStoreFullError(
                    f"store full ({self._used}/{self._capacity} bytes) and "
                    "all objects pinned")

    def _evict_one(self) -> bool:
        for oid, entry in self._entries.items():
            if entry.pin_count == 0:
                self._delete_locked(oid)
                return True
        return False

    def _delete_locked(self, object_id: ObjectID) -> None:
        entry = self._entries.pop(object_id, None)
        if entry is None:
            return
        self._used -= entry.size
        try:
            os.unlink(self.path_of(object_id))
        except FileNotFoundError:
            pass

    # ---- read path

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._entries

    def size_of(self, object_id: ObjectID) -> int | None:
        with self._lock:
            entry = self._entries.get(object_id)
            return entry.size if entry else None

    def touch(self, object_id: ObjectID) -> None:
        """LRU bump."""
        with self._lock:
            if object_id in self._entries:
                self._entries.move_to_end(object_id)

    def pin(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                raise ObjectLostError(object_id, "pin on missing object")
            entry.pin_count += 1

    def unpin(self, object_id: ObjectID) -> None:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is not None and entry.pin_count > 0:
                entry.pin_count -= 1

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._delete_locked(object_id)

    def list_objects(self) -> list[ObjectID]:
        with self._lock:
            return list(self._entries)

    def read_chunk(self, object_id: ObjectID, offset: int, length: int) -> bytes:
        """Read a chunk for cross-node transfer."""
        with self._lock:
            if object_id not in self._entries:
                raise ObjectLostError(object_id, "read on missing object")
            self._entries.move_to_end(object_id)
        with open(self.path_of(object_id), "rb") as f:
            f.seek(offset)
            return f.read(length)

    def destroy(self) -> None:
        with self._lock:
            for oid in list(self._entries):
                self._delete_locked(oid)
        try:
            os.rmdir(self._dir)
        except OSError:
            pass


class ObjectStoreFullError(ObjectLostError):
    def __init__(self, message: str):
        Exception.__init__(self, message)


def open_object(path: str) -> memoryview:
    """Client-side zero-copy read: mmap the sealed object file.

    The returned memoryview keeps the mapping alive; deserialized arrays
    referencing it remain valid even if the store evicts (unlinks) the file.
    """
    with open(path, "rb") as f:
        size = os.fstat(f.fileno()).st_size
        if size == 0:
            return memoryview(b"")
        mapping = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        return memoryview(mapping)


def default_store_capacity() -> int:
    """30% of system memory, capped by available tmpfs space."""
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page_size = os.sysconf("SC_PAGE_SIZE")
        total = pages * page_size
    except (ValueError, OSError):  # pragma: no cover
        total = 8 << 30
    cap = int(total * 0.3)
    try:
        stat = os.statvfs("/dev/shm")
        cap = min(cap, int(stat.f_bavail * stat.f_frsize * 0.8))
    except OSError:  # pragma: no cover
        pass
    return max(cap, 64 << 20)
