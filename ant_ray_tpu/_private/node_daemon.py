"""Per-node daemon ("raylet"-equivalent).

Role of the reference's raylet (ref: src/ray/raylet/node_manager.h:134,
worker_pool.h:285, local_object_manager.h): owns the node's worker pool and
shared-memory object store, grants worker leases against a local resource
view with spillback hints to other nodes, pulls remote objects in chunks,
monitors worker processes, and heartbeats the node's resource availability
to the GCS.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import random
import signal
import subprocess
import sys
import time
import uuid
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.ids import NodeID, ObjectID, WorkerID
from ant_ray_tpu._private.object_store import ObjectStore, default_store_capacity
from ant_ray_tpu._private.protocol import (
    ClientPool,
    IoThread,
    RawReply,
    RpcConnectionError,
    RpcError,
    RpcServer,
    RpcTimeoutError,
    _spawn,
)
from ant_ray_tpu._private.specs import ACTOR_DEAD, ActorSpec, NodeInfo

logger = logging.getLogger(__name__)


def _bundle_fits(bundle: dict, demand: dict) -> bool:
    """Whole-demand-within-bundle-capacity (shared by the prefetch gate
    and the grant/infeasible decision — they must never diverge)."""
    return all(bundle["resources"].get(k, 0.0) >= v
               for k, v in demand.items())


def _enable_subreaper() -> bool:
    """PR_SET_CHILD_SUBREAPER: a dead worker's user subprocesses
    re-parent to this daemon instead of init, so they can be detected
    and killed rather than leak (ref: src/ray/util/subreaper.h).
    Linux-only; returns False where unavailable."""
    if not sys.platform.startswith("linux"):
        return False
    try:
        import ctypes  # noqa: PLC0415

        libc = ctypes.CDLL(None, use_errno=True)
        PR_SET_CHILD_SUBREAPER = 36
        return libc.prctl(PR_SET_CHILD_SUBREAPER, 1, 0, 0, 0) == 0
    except Exception:  # noqa: BLE001 — best-effort hardening
        return False


class _HolderMiss(RuntimeError):
    """A GCS-listed holder no longer has the object (stale location)."""


class _NoViableHolder(RuntimeError):
    """Every GCS-listed holder missed the size probe (stale locations)
    or was unreachable — the pull round found nothing to pull from.
    ``any_unreachable`` distinguishes "all copies verifiably gone"
    (every miss retracted) from "holders exist but can't be reached
    right now" — only the former may feed the no-holders fail-fast that
    triggers lineage reconstruction."""

    def __init__(self, what: str, any_unreachable: bool = False):
        super().__init__(what)
        self.any_unreachable = any_unreachable

IDLE, LEASED, ACTOR, STARTING = "idle", "leased", "actor", "starting"

# Pin tokens for raw-RPC chunk serving (distinct namespace from the
# daemon's integer pin-lease tokens and the bulk channel's tokens).
_raw_serve_tokens = itertools.count()


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    proc: subprocess.Popen
    address: str = ""
    state: str = STARTING
    lease_resources: dict[str, float] = field(default_factory=dict)
    lease_pg: tuple | None = None        # (pg_id, bundle_index) if any
    lease_owner: str = ""                # lessee's core-service address
    actor_spec: ActorSpec | None = None
    job_id: object | None = None         # last job served (log scoping)
    blocked: bool = False
    env_key: str = ""                    # runtime-env pool identity
    registered: asyncio.Event = field(default_factory=asyncio.Event)


class NodeManager:
    def __init__(self, gcs_address: str, resources: dict[str, float],
                 session_dir: str, host: str = "127.0.0.1", port: int = 0,
                 labels: dict[str, str] | None = None):
        self.node_id = NodeID.from_random()
        self._gcs_address = gcs_address
        self._server = RpcServer(host, port)
        self._clients = ClientPool()
        self._io = IoThread.get()
        self._session_dir = session_dir
        # Auto-detected TPU slice labels (generation/pod/topology) merged
        # under explicit labels — this node process runs on the host being
        # described, so detection happens here, not in the launcher
        # (ref: node label advertisement for SlicePlacementGroup,
        # python/ray/util/tpu.py:52).
        from ant_ray_tpu._private.accelerators import tpu as _tpu  # noqa: PLC0415

        self._labels = {**_tpu.node_labels(), **(labels or {})}

        # The slice-head host (worker 0) advertises TPU-<pod_type>-head
        # so a slice can be exclusively claimed by reserving that single
        # unit resource (ref: python/ray/util/tpu.py:227).
        if self._labels.get("tpu-worker-id") == "0" and \
                self._labels.get("tpu-pod-type"):
            resources = dict(resources)
            resources.setdefault(
                f"TPU-{self._labels['tpu-pod-type']}-head", 1.0)

        cfg = global_config()
        store_capacity = cfg.object_store_memory or default_store_capacity()
        store_dir = os.path.join(
            "/dev/shm" if os.path.isdir("/dev/shm") else session_dir,
            f"art_{uuid.uuid4().hex[:8]}_{self.node_id.hex()[:8]}")
        spill_dir = (os.path.join(session_dir,
                                  f"spill_{self.node_id.hex()[:8]}")
                     if cfg.enable_object_spilling else None)
        self.store = ObjectStore(store_dir, store_capacity,
                                 on_delete=self._on_store_delete,
                                 spill_dir=spill_dir)

        self._total = dict(resources)
        self._available = dict(resources)
        # (pg_id, bundle_index) -> {"resources", "available", "committed"}
        self._bundles: dict[tuple, dict] = {}
        self._workers: dict[WorkerID, WorkerHandle] = {}
        # Spawned-but-unregistered workers: counted against the pool cap
        # so N concurrent lease requests can't each spawn (check-then-
        # spawn overshoot — a burst of leases on a small node must queue
        # for the pool, not fork a process storm).
        self._starting_workers = 0
        self._lease_event = asyncio.Event()
        self._max_workers = int(
            cfg.max_workers_per_node or max(1, int(resources.get("CPU", 1))))
        self._tasks: list = []
        self._stopping = False
        # object_id -> {pin_token: lease_expiry}, one per outstanding
        # arena read pin (see _locate_pinned / _reap_expired_pins).
        # Tokens let ReadDone/RenewPin address a specific reader's pin,
        # so a short-TTL reader finishing can't consume a long-lived
        # zero-copy reader's lease.
        self._pin_leases: dict[ObjectID, dict[int, float]] = {}
        self._next_pin_token = 1
        # Versioned-sync observability + early-send wakeup (see
        # _heartbeat_loop; ref: ray_syncer resource-view component).
        self.sync_stats = {"beats": 0, "views_sent": 0, "failures": 0}
        # In-flight lease-dep prefetch pulls, coalesced per object.
        self._prefetching: dict[ObjectID, asyncio.Task] = {}
        self._sync_wakeup = asyncio.Event()
        # Broadcast-serving chunk cache (ref: PushManager chunk dedup,
        # src/ray/object_manager/push_manager.h:28 — redesigned for the
        # pull-driven plane: N nodes fetching one object each read every
        # chunk from the holder, so the holder memoizes the chunk bytes
        # and pays ONE store read per chunk per broadcast, not N).
        self._chunk_cache: "OrderedDict[tuple, bytes]" = OrderedDict()
        self._chunk_cache_bytes = 0
        # Guards the chunk cache: served from the io loop (RPC chunk
        # reads) AND from bulk-transfer handler threads.
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._chunk_cache_lock = make_lock("daemon.chunk_cache")
        # Pull admission quota: bytes of in-flight inbound transfers
        # (ref: pull_manager.h:50 num_bytes_being_pulled quota) — callers
        # queue instead of pulling a dataset larger than memory at once.
        self._pull_bytes_inflight = 0
        self._pull_quota_cv: asyncio.Condition = asyncio.Condition()
        # pull_bytes_bulk vs pull_bytes_relayed split the pull volume by
        # path: holder-direct bulk-socket chunks vs chunks relayed
        # through the daemon RPC loop (ReadChunkRaw/ReadChunk fallback).
        # Their ratio is the `object_pull_relayed_fraction` gauge — the
        # "before" number for the owner-direct-pull plane (ROADMAP item
        # 2), which should drive it toward ~0.
        self.transfer_stats = {"chunk_reads": 0, "chunk_cache_hits": 0,
                               "quota_waits": 0, "stripe_cache_hits": 0,
                               "stripe_pulls": 0, "stripe_failovers": 0,
                               "holder_failures": 0, "pull_bytes": 0,
                               "pull_bytes_bulk": 0,
                               "pull_bytes_relayed": 0}
        # Holder-side log of served transfer-chunk requests (bounded),
        # for stripe tests/debugging: (object_hex, offset, length).
        self._chunk_read_log: deque = deque(maxlen=8192)
        # terminated-but-unreaped workers (retired for env mismatch)
        self._retired_procs: list[subprocess.Popen] = []
        # job_id -> (allowed_here, expires_at): virtual-cluster fencing
        self._vc_cache: dict = {}
        self.address = ""
        self._disk_full = False
        # Drain state (announced departure — TPU maintenance event,
        # SIGTERM, operator NotifyDrain): this node takes no NEW leases
        # but keeps serving its current work until it actually exits.
        self._draining = False
        self._drain_reason = ""
        self._drain_deadline_ts = 0.0

    # ------------------------------------------------------------ lifecycle

    def start(self) -> str:
        self._server.routes({
            "RegisterWorker": self._register_worker,
            "LeaseWorker": self._lease_worker,
            "ReturnWorker": self._return_worker,
            "WorkerBlocked": self._worker_blocked,
            "WorkerUnblocked": self._worker_unblocked,
            "StartActorWorker": self._start_actor_worker,
            "KillActorWorker": self._kill_actor_worker,
            "PrepareBundle": self._prepare_bundle,
            "CommitBundle": self._commit_bundle,
            "ReturnBundle": self._return_bundle,
            "SealObject": self._seal_object,
            "CreateBuffer": self._create_buffer,
            "SealBuffer": self._seal_buffer,
            "LocateObject": self._locate_object,
            "EnsureLocal": self._ensure_local,
            "ReadDone": self._read_done,
            "RenewPins": self._renew_pins,
            "ReadChunk": self._read_chunk,
            "DeleteObject": self._delete_object,
            "ContainsObject": self._contains_object,
            "GetNodeInfo": self._get_node_info,
            "NotifyDrain": self._notify_drain,
            "DebugResources": self._debug_resources,
            "GetSyncStats": self._get_sync_stats,
            "GetAgentInfo": self._get_agent_info,
            "GetStoreStats": self._get_store_stats,
            "ListObjectStats": self._list_object_stats,
            "GetNodeMetrics": self._get_node_metrics,
            "GetFlightRecorder": self._get_flight_recorder,
            "GetTransferStats": self._get_transfer_stats,
            "ListLogs": self._list_logs,
            "ReadLog": self._read_log,
            "Shutdown": self._shutdown_rpc,
        })
        # Sync fast route: the raw reply is written inline (no task
        # boundary), so an arena view can be served zero-copy — nothing
        # can evict/recycle the range before the transport consumes it.
        self._server.fast_route("ReadChunkRaw", self._read_chunk_raw)
        # Bulk data channel (transfer.py): holders advertise its port
        # via LocateObject probes; pullers that see one drain chunks
        # over blocking sockets instead of the control-plane RPC loop.
        from ant_ray_tpu._private.transfer import BulkServer  # noqa: PLC0415

        self._bulk = BulkServer(self, host=self._server._host)
        self._bulk_port = self._bulk.start()
        self.address = self._server.start()
        fut = asyncio.run_coroutine_threadsafe(self._register(), self._io.loop)
        fut.result(timeout=30)
        self._tasks.append(asyncio.run_coroutine_threadsafe(
            self._heartbeat_loop(), self._io.loop))
        self._tasks.append(asyncio.run_coroutine_threadsafe(
            self._monitor_workers_loop(), self._io.loop))
        if global_config().log_to_driver:
            self._tasks.append(asyncio.run_coroutine_threadsafe(
                self._log_stream_loop(), self._io.loop))
        self._subreaper_enabled = _enable_subreaper()
        self._start_agent()
        # cgroup v2 isolation (opt-in; ref: src/ray/common/cgroup2/ —
        # workers live in a sibling cgroup with a collective memory cap
        # so one blow-up can't take the daemon down).
        self._cgroups = None
        cfg = global_config()
        if cfg.enable_cgroups:
            from ant_ray_tpu._private.cgroup2 import CgroupManager  # noqa: PLC0415

            if CgroupManager.available(cfg.cgroup_root):
                mgr = CgroupManager(
                    os.path.basename(self._session_dir.rstrip("/"))
                    + "_" + self.node_id.hex()[:8],
                    root=cfg.cgroup_root,
                    workers_memory_max=cfg.cgroup_workers_memory_max,
                    workers_cpu_weight=cfg.cgroup_workers_cpu_weight)
                if mgr.setup():
                    mgr.add_system_process(os.getpid())
                    self._cgroups = mgr
                    logger.info("cgroup2 worker isolation active")
            else:
                logger.info("enable_cgroups set but no writable cgroup2 "
                            "tree; running without isolation")
        if global_config().fs_monitor_interval_s > 0:
            self._tasks.append(asyncio.run_coroutine_threadsafe(
                self._fs_monitor_loop(), self._io.loop))
        if global_config().memory_monitor_interval_s > 0:
            self._tasks.append(asyncio.run_coroutine_threadsafe(
                self._memory_monitor_loop(), self._io.loop))
        if global_config().preemption_poll_interval_s > 0:
            self._tasks.append(asyncio.run_coroutine_threadsafe(
                self._preemption_watch_loop(), self._io.loop))
        prestart = global_config().num_prestart_workers
        if prestart < 0:
            prestart = min(2, self._max_workers)
        for _ in range(min(prestart, self._max_workers)):
            self._io.run_coro(self._prestart_worker())
        # Fix this process's node identity on recorded spans (workers
        # inherit ART_NODE_ID via env; the daemon minted the id itself)
        # and give the recorder a publisher — the daemon is not an art
        # worker, so the default runtime-oneway channel is absent.
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        tracing_plane.set_node_id(self.node_id.hex())

        def _publish_spans(batch, manager=self):
            gcs = manager._clients.get(manager._gcs_address)
            asyncio.run_coroutine_threadsafe(
                gcs.oneway_async("SpanEventsAdd", {"spans": batch}),
                manager._io.loop)

        def _publish_metric(payload, manager=self):
            gcs = manager._clients.get(manager._gcs_address)
            asyncio.run_coroutine_threadsafe(
                gcs.oneway_async("MetricRecord", payload),
                manager._io.loop)

        tracing_plane.set_publisher(_publish_spans)
        tracing_plane.set_metric_recorder(_publish_metric)
        # Continuous CPU profiling: the daemon's sampler ships folded
        # stacks (and its wire-counter rollups) through the same
        # oneway-via-io-loop channel as the span publisher above.  An
        # instance profiler, not the module singleton — tests run
        # multiple daemons in one process.
        from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

        self._cpu_profiler = None
        if global_config().cpu_profile_hz > 0:
            def _publish_profile(record, manager=self):
                gcs = manager._clients.get(manager._gcs_address)
                asyncio.run_coroutine_threadsafe(
                    gcs.oneway_async("CpuProfileAdd",
                                     {"records": [record]}),
                    manager._io.loop)

            self._cpu_profiler = cpu_profiler.CpuProfiler(
                "daemon", publish_fn=_publish_profile,
                metric_fn=_publish_metric,
                node_id=self.node_id.hex()).start()
        logger.info("node %s listening on %s (resources=%s)",
                    self.node_id.hex()[:8], self.address, self._total)
        return self.address

    async def _prestart_worker(self):
        self._spawn_worker()

    def _node_info(self) -> NodeInfo:
        return NodeInfo(
            node_id=self.node_id,
            address=self.address,
            total_resources=dict(self._total),
            available_resources=dict(self._available),
            object_store_dir=self.store.directory,
            labels=dict(self._labels),
            draining=self._draining,
            drain_reason=self._drain_reason,
            drain_deadline=self._drain_deadline_ts,
        )

    async def _register(self):
        gcs = self._clients.get(self._gcs_address)
        await gcs.call_async("RegisterNode", self._node_info(), timeout=30)

    # ------------------------------------------------------ log monitor
    # (ref: python/ray/_private/log_monitor.py + the dashboard log
    # agent — here the node daemon itself serves its session logs, so
    # debugging worker N never needs ssh.)

    def _logs_dir(self) -> str:
        from ant_ray_tpu._private import log_serving  # noqa: PLC0415

        return log_serving.logs_dir(self._session_dir)

    async def _list_logs(self, _payload):
        from ant_ray_tpu._private import log_serving  # noqa: PLC0415

        return log_serving.list_logs(self._session_dir)

    async def _read_log(self, payload):
        from ant_ray_tpu._private import log_serving  # noqa: PLC0415

        return log_serving.read_log(self._session_dir, payload)

    async def _log_stream_loop(self):
        """Tail worker logs and fan new USER lines out to drivers via
        GCS pubsub (ref: log_monitor.py — `print()` inside a task shows
        up on the driver console as `(worker=.. pid=..) line`).  System
        lines (the worker's own `[worker ...]` logging format) stay in
        the file but are not streamed."""
        offsets: dict[str, int] = {}
        last_job: dict[str, object] = {}
        # name -> file offset below which lines predate the last
        # observed job switch (ship those unscoped).
        unscoped_below: dict[str, int] = {}
        gcs = self._clients.get(self._gcs_address)
        logs_dir = self._logs_dir()
        while not self._stopping:
            await asyncio.sleep(0.25)
            entries = []
            try:
                names = [n for n in os.listdir(logs_dir)
                         if n.startswith("worker-") and n.endswith(".log")]
            except OSError:
                continue
            for name in names:
                path = os.path.join(logs_dir, name)
                try:
                    size = os.path.getsize(path)
                    pos = offsets.get(name, 0)
                    if size <= pos:
                        continue
                    with open(path, "rb") as f:
                        f.seek(pos)
                        chunk = f.read(min(size - pos, 1 << 20))
                except OSError:
                    continue
                # keep any trailing partial line for the next pass —
                # unless the read window is full and newline-free (one
                # giant line): flush the whole window or the tail would
                # re-read it forever.
                cut = chunk.rfind(b"\n")
                if cut >= 0:
                    advance = cut + 1          # skip the newline
                elif len(chunk) >= (1 << 20):
                    cut = advance = len(chunk)  # flush, lose no bytes
                else:
                    continue
                offsets[name] = pos + advance
                short = name[len("worker-"):-len(".log")]
                handle = next((h for h in self._workers.values()
                               if h.worker_id.hex().startswith(short)),
                              None)
                pid = handle.proc.pid if handle else None
                job = None
                if handle is not None:
                    if handle.actor_spec is not None and \
                            handle.actor_spec.job_id is not None:
                        job = handle.actor_spec.job_id.hex()
                    elif handle.job_id is not None:
                        job = handle.job_id.hex()
                # Lines buffered across a lease boundary may belong to
                # the PREVIOUS job: on a job switch, everything already
                # in the file (up to its current size) ships unscoped —
                # every driver prints it — rather than scoped to the
                # wrong job and filtered off the right driver's
                # console.  A backlog larger than one read window stays
                # unscoped until the offset catches up to the switch
                # point.
                prev = last_job.get(name)
                if prev is not None and job is not None and prev != job:
                    unscoped_below[name] = size
                if job is not None:
                    last_job[name] = job
                if pos < unscoped_below.get(name, 0):
                    job = None
                lines = [ln.decode("utf-8", "replace")
                         for ln in chunk[:cut].split(b"\n")
                         if ln and not ln.startswith(b"[worker ")]
                if lines:
                    entries.append({"worker": short, "pid": pid,
                                    "job_id": job, "lines": lines})
            if entries:
                try:
                    await gcs.call_async(
                        "PublishLogs",
                        {"node": self.node_id.hex()[:8],
                         "entries": entries}, timeout=10)
                except Exception:  # noqa: BLE001 — head restarting
                    pass

    async def _get_node_info(self, _payload):
        return self._node_info()

    # ---------------------------------------------------------- draining
    # (announced departures: a TPU maintenance event / preemption notice
    #  arrives MINUTES before the host dies — reacting to it is the
    #  difference between a planned checkpoint+migrate and a surprise
    #  gang kill.  Ref: the reference's DrainNode protocol + the TPU
    #  maintenance-event watcher.)

    def begin_drain(self, reason: str = "",
                    deadline_s: float | None = None) -> bool:
        """Enter DRAINING: stop taking new leases, announce to the GCS.
        Idempotent; returns True on the first transition."""
        if self._draining:
            return False
        cfg = global_config()
        if deadline_s is None or deadline_s <= 0:
            deadline_s = cfg.drain_deadline_s
        self._draining = True
        self._drain_reason = reason or "drain requested"
        # Wall clock BY DESIGN: the deadline crosses processes in the
        # DrainNode payload / NodeInfo.DrainDeadline (specs.py).
        self._drain_deadline_ts = time.time() + deadline_s
        self._sync_wakeup.set()      # propagate via the next heartbeat
        logger.warning("node %s draining (%s; deadline in %.0fs)",
                       self.node_id.hex()[:8], self._drain_reason,
                       deadline_s)

        async def _announce():
            gcs = self._clients.get(self._gcs_address)
            payload = {"node_id": self.node_id,
                       "reason": self._drain_reason,
                       "deadline": self._drain_deadline_ts}
            for attempt in range(10):  # outlasts a head restart
                try:
                    await gcs.call_async("DrainNode", payload, timeout=10)
                    return
                except Exception:  # noqa: BLE001 — head restarting
                    await asyncio.sleep(min(0.2 * (attempt + 1), 2.0))
            # The heartbeat view carries the flag anyway — the direct
            # RPC only makes propagation immediate.

        # Fire-and-forget: begin_drain runs ON the io loop (NotifyDrain
        # handler) as well as off it (signal handler, watcher) — a
        # blocking run_coro here would deadlock the former.
        asyncio.run_coroutine_threadsafe(_announce(), self._io.loop)
        return True

    async def _notify_drain(self, payload):
        """Operator/test surface: drain THIS node (cluster_utils.
        drain_node, autoscaler downscale, chaos harness)."""
        payload = payload or {}
        return self.begin_drain(payload.get("reason", ""),
                                payload.get("deadline_s"))

    async def _preemption_watch_loop(self):
        """Poll for a pending TPU maintenance event / preemption notice
        (accelerators.tpu.maintenance_notice — GCE metadata in
        production, the testing_preemption_notice file under chaos) and
        self-drain when one fires."""
        from ant_ray_tpu._private.accelerators import tpu as _tpu  # noqa: PLC0415

        cfg = global_config()
        if not _tpu.maintenance_watch_possible():
            return   # no notice source on this host: don't poll forever
        period = cfg.preemption_poll_interval_s
        file_knob = bool(cfg.testing_preemption_notice)
        while not self._stopping:
            await asyncio.sleep(period)
            if self._draining:
                return            # terminal: nothing left to watch
            try:
                if file_knob:
                    # File-existence probe: microseconds, safe inline.
                    notice = _tpu.maintenance_notice()
                else:
                    # Metadata probe can stall on DNS — off the io loop.
                    notice = await asyncio.to_thread(
                        _tpu.maintenance_notice)
            except Exception:  # noqa: BLE001 — detection is best-effort
                continue
            if notice is not None:
                reason, deadline_s = notice
                self.begin_drain(f"preemption notice: {reason}",
                                 deadline_s or None)
                return

    async def _debug_resources(self, _payload):
        """Resource-ledger dump for `art stack`-style debugging: who
        holds what, which workers are blocked, and each bundle pool."""
        return {
            "available": dict(self._available),
            "bundles": {f"{k[0].hex() if hasattr(k[0], 'hex') else k[0]}"
                        f"#{k[1]}": {"capacity": dict(b["resources"]),
                                     "available": dict(b["available"])}
                        for k, b in self._bundles.items()},
            "workers": [{
                "worker_id": wid.hex() if hasattr(wid, "hex") else str(wid),
                "state": h.state,
                "blocked": h.blocked,
                "lease": dict(h.lease_resources or {}),
                "actor": (h.actor_spec.class_name
                          if h.actor_spec is not None and
                          hasattr(h.actor_spec, "class_name")
                          else (h.actor_spec.actor_id.hex()
                                if h.actor_spec is not None else None)),
                "actor_resources": (dict(h.actor_spec.resources)
                                    if h.actor_spec is not None else None),
            } for wid, h in self._workers.items()],
        }

    async def _get_sync_stats(self, _payload):
        return dict(self.sync_stats)

    async def _get_agent_info(self, _payload):
        proc = getattr(self, "_agent_proc", None)
        return {"address": getattr(self, "_agent_address", None),
                "alive": proc is not None and proc.poll() is None,
                "restarts": getattr(self, "_agent_restarts", 0)}

    async def _get_store_stats(self, _payload):
        return {"used": self.store.used,
                "capacity": self.store.capacity,
                "spilled": self.store.spilled_bytes}

    async def _list_object_stats(self, _payload):
        """Per-object arena residency (size / pins / tier) plus this
        holder's chunk-cache footprint per object — the daemon half of
        the memory-attribution join (`art memory`, /api/memory,
        /api/objects all read this; the GCS directory contributes
        locations + owner)."""
        objects = self.store.object_stats()
        with self._chunk_cache_lock:
            cache_by_oid: dict[str, int] = {}
            for (oid, _offset, _length), data in \
                    self._chunk_cache.items():
                hexid = oid.hex()
                cache_by_oid[hexid] = \
                    cache_by_oid.get(hexid, 0) + len(data)
        for entry in objects:
            entry["chunk_cache_bytes"] = cache_by_oid.get(
                entry["object_id"], 0)
        return {"node_id": self.node_id.hex(),
                "objects": objects,
                "store": {"used": self.store.used,
                          "capacity": self.store.capacity,
                          "spilled": self.store.spilled_bytes}}

    async def _get_flight_recorder(self, payload):
        """This daemon process's flight-recorder ring (always on): the
        live spans — including force-sampled error spans — even when
        the batch publisher lags or the GCS ring wrapped.  The
        dashboard's ``GET /api/flightrecorder?node_id=`` lands here."""
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        limit = int((payload or {}).get("limit", 0) or 0)
        return {"node_id": self.node_id.hex(),
                "spans": tracing_plane.recorder().snapshot(limit)}

    async def _get_node_metrics(self, _payload):
        """Per-node gauges for the head's /metrics aggregation (role of
        the reference's per-node metrics agents, dashboard/agent.py:24 +
        _private/metrics_agent.py — the daemon exports its own numbers
        over RPC, no extra agent process per node)."""
        series = [
            ("art_node_store_used_bytes", self.store.used,
             "object store bytes in use"),
            ("art_node_store_capacity_bytes", self.store.capacity,
             "object store capacity"),
            ("art_node_store_spilled_bytes", self.store.spilled_bytes,
             "bytes spilled to disk"),
            ("art_node_workers", len(self._workers),
             "registered workers"),
            ("art_node_read_pins", len(self._pin_leases),
             "objects held by read pins"),
            ("art_node_heartbeat_failures_total",
             self.sync_stats["failures"],
             "heartbeat sends that failed (flapping GCS link)"),
        ]
        try:
            load1 = os.getloadavg()[0]
            series.append(("art_node_load1", load1, "1m load average"))
        except OSError:  # pragma: no cover
            pass
        try:
            with open("/proc/meminfo") as f:
                mem = {}
                for line in f:
                    parts = line.split()
                    if parts[0] in ("MemTotal:", "MemAvailable:"):
                        mem[parts[0]] = int(parts[1]) * 1024
            series.append(("art_node_mem_total_bytes",
                           mem.get("MemTotal:", 0), "host memory"))
            series.append(("art_node_mem_available_bytes",
                           mem.get("MemAvailable:", 0),
                           "host memory available"))
        except OSError:  # pragma: no cover — non-Linux
            pass
        for key, value in self._available.items():
            series.append(("art_node_resource_available",
                           value, "available resource", {"resource": key}))
        # Transfer-plane counters (windowed/striped pull scheduler +
        # holder-side chunk cache) as gauges for the head aggregation.
        for key, value in self.transfer_stats.items():
            series.append((f"art_node_transfer_{key}", value,
                           "object transfer-plane counter"))
        series.append(("art_node_transfer_chunk_cache_bytes",
                       self._chunk_cache_bytes,
                       "holder-side transfer chunk cache bytes"))
        series.append(("art_node_object_pull_relayed_fraction",
                       self._pull_relayed_fraction(),
                       "fraction of pulled bytes relayed through the "
                       "daemon RPC path instead of holder-direct bulk"))
        return [
            {"name": name, "type": "gauge", "value": float(value),
             "description": desc,
             "tags": (extra[0] if extra else {})}
            for name, value, desc, *extra in series
        ]

    async def _heartbeat_loop(self):
        """Liveness heartbeat + versioned resource sync (ref:
        src/ray/ray_syncer/ray_syncer.h:90 — versioned per-node state
        gossip with "don't resend what the peer knows" semantics).

        The resource view rides the heartbeat ONLY when it changed
        since the version the GCS last acked: an idle cluster's beats
        carry just the node id, so steady-state sync bytes are O(1) per
        node instead of O(resource-dict).  A change wakes the loop
        early (sub-period propagation — fresher than the fixed beat the
        full-view design had), and the GCS can command a resync after
        losing state.  Version bumps come from snapshot comparison, not
        from instrumenting every mutation site, so a missed wakeup can
        delay a delta by at most one period, never lose it."""
        gcs = self._clients.get(self._gcs_address)
        cfg = global_config()
        period = cfg.heartbeat_period_s
        if cfg.heartbeat_jitter and period > 0:
            # Phase-stagger by a hash of the node id: N daemons booted
            # together spread their beats across the period instead of
            # slamming the GCS io loop in lockstep every period.
            phase = (int(self.node_id.hex()[:8], 16) % 997) / 997.0
            await asyncio.sleep(phase * period)
        last_snap = None
        version = 0
        acked = -1
        last_gcs_ok = time.monotonic()
        consecutive_failures = 0
        while not self._stopping:
            snap = (tuple(sorted(self._available.items())),
                    self._disk_full, self._draining)
            if snap != last_snap:
                last_snap = snap
                version += 1
            payload: dict = {"node_id": self.node_id}
            if version > acked:
                payload["view"] = {
                    "available_resources": dict(self._available),
                    "disk_full": self._disk_full,
                    "draining": self._draining,
                    "drain_reason": self._drain_reason,
                    "drain_deadline": self._drain_deadline_ts,
                    "version": version,
                }
            try:
                reply = await gcs.call_async("Heartbeat", payload,
                                             timeout=10)
                if reply.get("unknown_node"):
                    await self._register()
                    acked = -1
                else:
                    if "synced" in reply:
                        acked = max(acked, reply["synced"])
                    if "resync" in reply.get("commands", ()):
                        acked = -1
                self.sync_stats["beats"] += 1
                if "view" in payload:
                    self.sync_stats["views_sent"] += 1
                last_gcs_ok = time.monotonic()
                consecutive_failures = 0
            except Exception as e:  # noqa: BLE001 — head may be restarting
                logger.debug("heartbeat failed: %s", e)
                # A flapping link must be VISIBLE (counter surfaces as
                # art_node_heartbeat_failures_total) and must not
                # busy-spin: consecutive failures back the loop off
                # exponentially, capped well under the death timeout so
                # one recovered beat still lands in time.
                self.sync_stats["failures"] += 1
                consecutive_failures += 1
                # Fail-stop on a permanently-gone head: GCS restarts
                # (FT) come back within seconds; a daemon orphaned by a
                # dead cluster must not linger burning CPU forever.
                dead_after = global_config().gcs_dead_exit_s
                if dead_after > 0 and \
                        time.monotonic() - last_gcs_ok > dead_after:
                    logger.error(
                        "GCS unreachable for %.0fs; node daemon "
                        "exiting", time.monotonic() - last_gcs_ok)
                    os._exit(1)
            self._reap_expired_pins()
            wait = period
            if consecutive_failures > 1:
                wait = max(period, min(
                    period * (2 ** (consecutive_failures - 1)),
                    global_config().heartbeat_backoff_cap_s))
            self._sync_wakeup.clear()
            try:
                await asyncio.wait_for(self._sync_wakeup.wait(), wait)
            except asyncio.TimeoutError:
                pass

    def stop(self):
        self._stopping = True
        profiler = getattr(self, "_cpu_profiler", None)
        if profiler is not None:
            self._cpu_profiler = None
            profiler.stop(final_publish=False)
        for t in self._tasks:
            t.cancel()
        # Destroy the store first: everything after can take seconds and
        # the parent's kill-grace window is short — tmpfs cleanup must
        # never lose the race.
        self.store.destroy()
        bulk = getattr(self, "_bulk", None)
        if bulk is not None:
            bulk.stop()
        self._server.stop()
        for handle in list(self._workers.values()):
            if handle.proc.poll() is None:
                handle.proc.terminate()
        deadline = time.monotonic() + 3
        for handle in list(self._workers.values()):
            remaining = max(0.05, deadline - time.monotonic())
            try:
                handle.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                handle.proc.kill()
        for proc in self._retired_procs:
            if proc.poll() is None:
                proc.kill()
        agent = getattr(self, "_agent_proc", None)
        if agent is not None and agent.poll() is None:
            agent.terminate()
        if self._cgroups is not None:
            self._cgroups.cleanup()
        self._clients.close_all()

    async def _shutdown_rpc(self, _payload):
        asyncio.get_running_loop().call_later(0.05, self.stop)
        return True

    # ------------------------------------------------------------ workers

    def _spawn_worker(self, actor_spec: ActorSpec | None = None,
                      runtime_env: dict | None = None) -> WorkerHandle:
        from ant_ray_tpu._private import runtime_env as renv  # noqa: PLC0415

        if actor_spec is not None and runtime_env is None:
            runtime_env = actor_spec.runtime_env
        worker_id = WorkerID.from_random()
        from ant_ray_tpu._private import services  # noqa: PLC0415

        # Workers run accelerator code: restore the TPU-plugin trigger
        # the control-plane env stashed (no-op under the CPU pin).
        env = services.accelerator_env(dict(os.environ))
        cwd = None
        if runtime_env:
            # packages were prefetched by _ensure_runtime_env (async);
            # resolve() is pure path logic, safe on the event loop
            overlay, cwd = renv.resolve(runtime_env, self._session_dir)
            env.update(overlay)
            # A staged cwd loses the implicit cwd-based import of a
            # checkout-run framework — pin the package root explicitly.
            renv.ensure_framework_on_pythonpath(env)
        env["ART_NODE_ADDRESS"] = self.address
        env["ART_GCS_ADDRESS"] = self._gcs_address
        # Worker stdout is a log file (block-buffered by default): run
        # unbuffered so user print()s stream to the driver promptly.
        env["PYTHONUNBUFFERED"] = "1"
        env["ART_STORE_DIR"] = self.store.directory
        env["ART_WORKER_ID"] = worker_id.hex()
        env["ART_NODE_ID"] = self.node_id.hex()
        log_path = os.path.join(self._session_dir, "logs",
                                f"worker-{worker_id.hex()[:8]}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        log_file = open(log_path, "ab")
        # A pip env's workers run on its venv interpreter (built by
        # _ensure_runtime_env before the spawn reaches here).
        python = renv.venv_python(runtime_env, self._session_dir) \
            or sys.executable
        proc = subprocess.Popen(
            [python, "-m", "ant_ray_tpu._private.worker_main"],
            env=env, cwd=cwd, stdout=log_file, stderr=subprocess.STDOUT,
            start_new_session=True)
        log_file.close()
        handle = WorkerHandle(worker_id, proc, actor_spec=actor_spec,
                              env_key=renv.env_key(runtime_env))
        if self._cgroups is not None:
            self._cgroups.add_worker_process(proc.pid)
        self._workers[worker_id] = handle
        return handle

    async def _register_worker(self, payload):
        worker_id = payload["worker_id"]
        handle = self._workers.get(worker_id)
        if handle is None:
            return {"error": "unknown worker"}
        handle.address = payload["address"]
        was_actor = handle.actor_spec is not None
        if was_actor:
            client = self._clients.get(handle.address)
            _spawn(
                client.call_async("InstantiateActor", handle.actor_spec,
                                  timeout=-1))
            handle.state = ACTOR
        else:
            handle.state = IDLE
            self._lease_event.set()
        handle.registered.set()
        return {"ok": True}

    async def _monitor_workers_loop(self):
        gcs = self._clients.get(self._gcs_address)
        last_orphan_sweep = 0.0
        while not self._stopping:
            await asyncio.sleep(0.1)
            if self._retired_procs:
                self._retired_procs = [p for p in self._retired_procs
                                       if p.poll() is None]
            now = time.monotonic()
            self._supervise_agent()
            if self._subreaper_enabled and now - last_orphan_sweep > 2.0:
                last_orphan_sweep = now
                self._reap_orphans()
            self._sweep_lease_owners(now)
            for worker_id, handle in list(self._workers.items()):
                if handle.proc.poll() is None:
                    continue
                del self._workers[worker_id]
                # A dead worker may itself be a lessee (nested task
                # submission): reclaim whatever it still leased.
                self._reclaim_leases_of(handle.address)
                if handle.state == LEASED and not handle.blocked:
                    if handle.lease_pg is not None:
                        self._bundle_release(handle.lease_pg,
                                             handle.lease_resources)
                    else:
                        self._release(handle.lease_resources)
                if handle.state == ACTOR and handle.actor_spec is not None:
                    if not handle.blocked:  # blocked already released
                        self._release_actor_resources(handle.actor_spec)
                    # Death reports must survive a GCS restart window —
                    # fire-and-forget here loses the actor forever
                    # (restored as ALIVE on resync with no one to
                    # correct it), so retry in the background.
                    _spawn(self._report_worker_died(
                        gcs, worker_id, handle))
                self._lease_event.set()

    def _reap_orphans(self) -> None:
        """Kill + reap grandchildren re-parented to this daemon by the
        subreaper (a dead worker's user subprocesses).  Direct children
        the daemon spawned itself (workers, runtime-env builds) share
        its session or are registered — only processes from a *foreign*
        session that aren't known workers are orphans (ref:
        src/ray/util/subreaper.h kill-unknown-children policy)."""
        known = {h.proc.pid for h in self._workers.values()}
        known |= {p.pid for p in self._retired_procs}
        agent = getattr(self, "_agent_proc", None)
        if agent is not None:
            # The node agent is a daemon child in its own session — the
            # foreign-session heuristic would reap it every sweep.
            known.add(agent.pid)
        my_pid = os.getpid()
        try:
            my_sid = os.getsid(0)
        except OSError:
            return
        try:
            candidates = [int(n) for n in os.listdir("/proc")
                          if n.isdigit()]
        except OSError:
            return
        for pid in candidates:
            # NEVER waitpid(-1): reaping a known worker here would
            # steal its exit status from Popen.poll() and turn every
            # death reason into "exited with code 0".
            if pid in known or pid == my_pid:
                continue
            try:
                with open(f"/proc/{pid}/stat") as f:
                    fields = f.read().rsplit(")", 1)[1].split()
                state, ppid = fields[0], int(fields[1])
                if ppid != my_pid:
                    continue
                # Session check FIRST, zombies included: a transient
                # subprocess.run child of a daemon executor thread (a
                # runtime-env build) shares our session — waitpid'ing
                # its zombie here would steal the exit status its
                # spawner is about to collect (ECHILD -> returncode 0,
                # a failed build reported as success).
                if os.getsid(pid) == my_sid:
                    continue
                if state == "Z":               # orphan already exited
                    os.waitpid(pid, os.WNOHANG)
                    continue
                os.kill(pid, signal.SIGKILL)
                os.waitpid(pid, os.WNOHANG)
                logger.info("reaped orphaned process %d (parent worker "
                            "died)", pid)
            except (OSError, ValueError, IndexError):
                continue

    async def _report_worker_died(self, gcs, worker_id, handle):
        payload = {
            "node_id": self.node_id,
            "worker_id": worker_id,
            "actor_id": handle.actor_spec.actor_id,
            "reason": f"worker exited with code "
                      f"{handle.proc.returncode}",
        }
        for attempt in range(30):  # ~60s: outlasts a head restart
            try:
                await gcs.call_async("WorkerDied", payload, timeout=10)
                return
            except Exception:  # noqa: BLE001 — head may be restarting
                await asyncio.sleep(min(0.2 * (attempt + 1), 2.0))
        logger.warning("giving up reporting death of worker %s",
                       worker_id)

    # ------------------------------------------------- memory monitor
    # (ref: src/ray/common/memory_monitor.h — cgroup/proc-based node OOM
    #  detection; src/ray/raylet/worker_killing_policy.h — retriable
    #  tasks die before actors, largest first, so the node survives
    #  memory pressure instead of being OOM-killed wholesale)

    @staticmethod
    def _read_memory_used_fraction(meminfo_path: str) -> float | None:
        try:
            fields = {}
            with open(meminfo_path) as f:
                for line in f:
                    key, _, rest = line.partition(":")
                    fields[key.strip()] = int(rest.strip().split()[0])
            total = fields.get("MemTotal", 0)
            available = fields.get("MemAvailable")
            if total <= 0 or available is None:
                # No MemAvailable (old kernel / minimal proc fake):
                # better no monitoring than reading "100% used" and
                # killing healthy workers every tick.
                return None
            return 1.0 - available / total
        except (OSError, ValueError, IndexError):
            return None

    def _worker_rss_kb(self, handle: WorkerHandle) -> int:
        try:
            with open(f"/proc/{handle.proc.pid}/statm") as f:
                return int(f.read().split()[1]) * 4  # pages → ~KiB
        except (OSError, ValueError, IndexError):
            return 0

    def _pick_oom_victim(self) -> WorkerHandle | None:
        """Retriable first (leased task workers — their tasks retry),
        then actors (they may restart); idle/starting workers are free
        memory already being reclaimed, never victims."""
        candidates = [h for h in self._workers.values()
                      if h.state in (LEASED, ACTOR)
                      and h.proc.poll() is None]  # corpses free nothing
        if not candidates:
            return None
        return max(candidates,
                   key=lambda h: (h.state == LEASED,
                                  self._worker_rss_kb(h)))

    # ---------------------------------------------- filesystem monitor
    # (ref: src/ray/common/file_system_monitor.h — a node whose local
    #  disk crosses the capacity threshold stops accepting new leases,
    #  redirecting work to nodes that can still spill/log)

    def _read_disk_used_fraction(self) -> float | None:
        import shutil  # noqa: PLC0415

        try:
            usage = shutil.disk_usage(self._session_dir or "/tmp")
            return usage.used / usage.total if usage.total else None
        except OSError:
            return None

    async def _fs_monitor_loop(self):
        cfg = global_config()
        while not self._stopping:
            used = self._read_disk_used_fraction()
            full = (used is not None
                    and used >= cfg.local_fs_capacity_threshold)
            if full and not self._disk_full:
                logger.warning(
                    "local disk %.1f%% full (>= %.1f%%): node stops "
                    "accepting new leases until space frees",
                    100 * used, 100 * cfg.local_fs_capacity_threshold)
            if full != self._disk_full:
                self._disk_full = full
                self._sync_wakeup.set()
            await asyncio.sleep(cfg.fs_monitor_interval_s)

    async def _memory_monitor_loop(self):
        cfg = global_config()
        while not self._stopping:
            await asyncio.sleep(cfg.memory_monitor_interval_s)
            used = self._read_memory_used_fraction(cfg.meminfo_path)
            if used is None or used < cfg.memory_usage_threshold:
                continue
            victim = self._pick_oom_victim()
            if victim is None:
                continue
            logger.warning(
                "memory pressure (%.1f%% used >= %.1f%%): killing "
                "worker %s (%s, rss=%dKiB) to relieve it",
                100 * used, 100 * cfg.memory_usage_threshold,
                victim.worker_id.hex()[:8], victim.state,
                self._worker_rss_kb(victim))
            self._terminate_worker(victim)
            # Death propagation (task retry / actor restart) runs via
            # the normal worker monitor; pause a beat so the kill lands
            # before the next pressure reading.
            await asyncio.sleep(cfg.memory_monitor_interval_s)

    def _terminate_worker(self, handle: WorkerHandle):
        if handle.proc.poll() is None:
            handle.proc.terminate()
            try:
                handle.proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                handle.proc.kill()

    # ------------------------------------------------------------ leasing

    def _can_allocate(self, demand: dict[str, float]) -> bool:
        return all(self._available.get(k, 0.0) >= v for k, v in demand.items())

    def _feasible(self, demand: dict[str, float]) -> bool:
        return all(self._total.get(k, 0.0) >= v for k, v in demand.items())

    def _allocate(self, demand: dict[str, float]):
        for k, v in demand.items():
            self._available[k] = self._available.get(k, 0.0) - v
        self._sync_wakeup.set()

    def _release(self, demand: dict[str, float]):
        for k, v in demand.items():
            self._available[k] = self._available.get(k, 0.0) + v
        self._lease_event.set()
        self._sync_wakeup.set()

    # ---------------------------------------------------- agent manager
    # (ref: src/ray/raylet/agent_manager.h — the raylet spawns and
    #  supervises per-node agent processes; runtime-env builds run in
    #  the agent so a slow/crashing build can't take the daemon down)

    def _start_agent(self) -> None:
        if not global_config().enable_node_agent:
            return
        from ant_ray_tpu._private import services  # noqa: PLC0415

        os.makedirs(os.path.join(self._session_dir, "logs"),
                    exist_ok=True)
        agent_env = services.control_plane_env()
        # The agent tags its published device gauges with the node id
        # (per-node series identity + death-time expiry in the GCS).
        agent_env["ART_NODE_ID"] = self.node_id.hex()
        self._agent_proc = subprocess.Popen(
            [sys.executable, "-m", "ant_ray_tpu._private.node_agent",
             "--session-dir", self._session_dir,
             "--gcs-address", self._gcs_address,
             "--monitor-pid", str(os.getpid())],
            stdout=subprocess.PIPE,
            stderr=open(os.path.join(self._session_dir, "logs",
                                     "agent.err"), "ab"),
            env=agent_env, start_new_session=True)
        self._agent_address = None

        def _wait_ready(proc=self._agent_proc):
            # Off-thread READY wait: daemon boot never blocks on the
            # agent; builds fall back in-process until it reports in.
            try:
                for line in proc.stdout:
                    text = line.decode(errors="replace").strip()
                    if text.startswith("AGENT_READY"):
                        if self._agent_proc is proc:
                            self._agent_address = text.split(" ", 1)[1]
                        return
            except Exception:  # noqa: BLE001
                pass

        import threading  # noqa: PLC0415

        threading.Thread(target=_wait_ready, daemon=True).start()

    def _supervise_agent(self) -> None:
        """Restart a dead agent (called from the worker-monitor loop)
        with a simple backoff."""
        proc = getattr(self, "_agent_proc", None)
        if proc is None or proc.poll() is None:
            return
        now = time.monotonic()
        if now < getattr(self, "_agent_backoff_until", 0.0):
            return
        self._agent_backoff_until = now + min(
            2.0 * (getattr(self, "_agent_restarts", 0) + 1), 30.0)
        self._agent_restarts = getattr(self, "_agent_restarts", 0) + 1
        # Clear the dead address NOW — during the backoff window every
        # build would otherwise dial it first and pay a failed connect.
        self._agent_address = None
        logger.warning("node agent died (exit %s); restarting",
                       proc.returncode)
        self._start_agent()

    async def _ensure_runtime_env(self, wire: dict | None):
        """Prefetch + extract a runtime env's packages (working_dir +
        py_modules) and build its pip venv, so the (sync) worker spawn
        only touches local paths.  Delegated to the node agent when one
        is serving (build isolation, ref: runtime_env_agent.py:167);
        falls back in-process while the agent is down/booting."""
        from ant_ray_tpu._private import runtime_env as renv  # noqa: PLC0415

        if not wire or renv.is_ready(wire, self._session_dir):
            return  # fully materialized: no RPC, no executor hop
        agent_addr = getattr(self, "_agent_address", None)
        if agent_addr:
            try:
                reply = await self._clients.get(agent_addr).call_async(
                    "BuildRuntimeEnv", {"wire": wire}, timeout=1800)
                if reply.get("ok"):
                    return
                raise RuntimeError(reply.get("error", "agent build failed"))
            except RuntimeError:
                raise
            except Exception as e:  # noqa: BLE001 — agent died mid-build
                logger.warning("agent env build unavailable (%s); "
                               "building in-process", e)
        gcs = self._clients.get(self._gcs_address)

        async def kv_get(key):
            return await gcs.call_async("KVGet", {"key": key}, timeout=60)

        await renv.materialize(wire, self._session_dir, kv_get)

    async def _job_allowed_here(self, job_id) -> bool:
        """Virtual-cluster membership of this node for a job, cached
        briefly (VC edits are rare; a 5s-stale view only delays
        re-fencing, never correctness of results)."""
        now = time.monotonic()
        cached = self._vc_cache.get(job_id)
        if cached is not None and cached[1] > now:
            return cached[0]
        gcs = self._clients.get(self._gcs_address)
        try:
            reply = await gcs.call_async(
                "GetJobVirtualCluster", {"job_id": job_id}, timeout=10)
            allowed_hex = reply.get("allowed_node_ids")
            allowed = (allowed_hex is None
                       or self.node_id.hex() in allowed_hex)
        except Exception:  # noqa: BLE001 — fail open on GCS hiccups
            allowed = True
        if len(self._vc_cache) > 256:
            self._vc_cache = {k: v for k, v in self._vc_cache.items()
                              if v[1] > now}
        self._vc_cache[job_id] = (
            allowed, now + global_config().vc_fence_ttl_s)
        return allowed

    def _idle_worker(self, env_key: str = "") -> WorkerHandle | None:
        for handle in self._workers.values():
            if (handle.state == IDLE and handle.address
                    and handle.env_key == env_key):
                # Liveness check at grant: a worker that died while
                # leased gets ReturnWorker'd back to IDLE by its driver
                # before the reaper runs — handing out the corpse makes
                # every fast retry burn an attempt on a dead port.
                if handle.proc.poll() is not None:
                    continue  # reaper will collect it
                return handle
        return None

    def _retire_idle_mismatch(self, env_key: str) -> bool:
        """Kill one idle worker of a *different* runtime env so a full
        pool can still serve a new env (ref: WorkerPool eviction of
        idle workers for mismatched runtime envs).  Non-blocking: the
        monitor loop reaps the terminated process."""
        for worker_id, handle in list(self._workers.items()):
            if (handle.state == IDLE and handle.env_key != env_key
                    and handle.actor_spec is None):
                del self._workers[worker_id]
                if handle.proc.poll() is None:
                    handle.proc.terminate()
                self._retired_procs.append(handle.proc)
                return True
        return False

    def _pool_size(self) -> int:
        """Workers counted against the pool cap: task workers that are
        actually occupying a cpu.  Blocked workers (parked in get()) and
        dedicated actor workers don't count, so nested task chains can
        always make progress (ref: worker_pool starts workers beyond
        num_cpus when existing ones are blocked)."""
        return sum(1 for h in self._workers.values()
                   if h.actor_spec is None and not h.blocked)

    async def _lease_worker(self, payload):
        """Grant a worker lease or reply with a spillback target
        (ref: NodeManager::HandleRequestWorkerLease, node_manager.cc:1794).

        Traced leases (payload carries the head task's ``trace`` wire
        context) record a ``daemon:lease`` child span with the grant
        outcome, so a slow actor call can be attributed to scheduling
        rather than execution."""
        wire = payload.get("trace")
        if wire is None:
            return await self._lease_worker_impl(payload)
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        with tracing_plane.server_span(wire, "daemon:lease",
                                       "LeaseWorker") as sp:
            sp.attrs = {"outcome": "error",
                        "resources": dict(payload.get("resources", {}))}
            reply = await self._lease_worker_impl(payload)
            sp.attrs["outcome"] = next(iter(reply))
            sp.error = "infeasible" in reply
            return reply

    async def _lease_worker_impl(self, payload):
        demand: dict[str, float] = payload.get("resources", {})
        gcs = self._clients.get(self._gcs_address)
        from ant_ray_tpu._private import runtime_env as renv  # noqa: PLC0415

        pg_key = payload.get("pg")
        job_id = payload.get("job_id")
        selector = payload.get("label_selector")
        strategy = payload.get("strategy")
        # Hard node affinity: this lease must run HERE once routed —
        # every redirect path below turns into an infeasible error
        # instead of a spill that would break the pin.
        pinned_here = (pg_key is None and isinstance(strategy, dict)
                       and strategy.get("kind") == "node_affinity"
                       and not strategy.get("soft")
                       and strategy["node_id"] == self.node_id.hex())
        # Strategy routing (ref: the raylet policy set,
        # composite_scheduling_policy.h:33).  PG leases are exempt —
        # the bundle reservation already placed them.  A lease that
        # already followed a strategy redirect carries "routed" (set by
        # the client on strategy spills) and is served where it landed —
        # re-running the picker on every hop would ping-pong forever
        # (the spread cursor advances per query, so it never returns
        # the node currently asking).
        if pg_key is None and strategy is not None and \
                not payload.get("routed"):
            if strategy == "SPREAD":
                node = await gcs.call_async(
                    "SelectNode",
                    {"resources": demand, "job_id": job_id,
                     "label_selector": selector,
                     "strategy": "SPREAD"}, timeout=10)
                if node is not None and node.node_id != self.node_id:
                    return {"spill": node.address, "routed": True}
                # self is the spread pick (or nothing feasible yet):
                # serve locally below.
            elif isinstance(strategy, dict) and \
                    strategy.get("kind") == "node_affinity":
                target_hex = strategy["node_id"]
                if self.node_id.hex() != target_hex:
                    infos = await gcs.call_async("GetAllNodes", {},
                                                 timeout=10)
                    target = next(
                        (n for n in infos.values()
                         if n.node_id.hex() == target_hex and n.alive),
                        None)
                    if target is not None:
                        return {"spill": target.address, "routed": True}
                    if not strategy.get("soft"):
                        return {"infeasible": True,
                                "reason": f"node-affinity target "
                                          f"{target_hex[:12]} is not "
                                          "alive"}
                    # soft affinity on a dead node: DEFAULT placement.
        # A label-constrained lease on a non-matching node redirects
        # immediately (the GCS picks a matching node); PG leases are
        # exempt — the bundle was placed under the selector already.
        if pg_key is None and selector and not all(
                self._labels.get(k) == v for k, v in selector.items()):
            if pinned_here:
                return {"infeasible": True,
                        "reason": "node-affinity target does not match "
                                  f"label selector {selector}"}
            node = await gcs.call_async(
                "SelectNode", {"resources": demand, "job_id": job_id,
                               "exclude": self.node_id,
                               "label_selector": selector}, timeout=10)
            if node is not None and node.node_id != self.node_id:
                return {"spill": node.address}
            return {"infeasible": True,
                    "reason": f"no node matches label selector {selector}"}
        # Virtual-cluster fencing: if this node isn't in the job's
        # allowed set, redirect before doing any work here (ant-fork
        # ref: node_manager.ant.cc cancels mismatched leases).  PG
        # leases are exempt — the bundle reservation (placed under the
        # VC filter at creation time) is the authority.
        if pg_key is None and job_id is not None and \
                not await self._job_allowed_here(job_id):
            if pinned_here:
                return {"infeasible": True,
                        "reason": "node-affinity target is outside the "
                                  "job's virtual cluster"}
            node = await gcs.call_async(
                "SelectNode", {"resources": demand, "job_id": job_id,
                               "exclude": self.node_id,
                               "label_selector": selector}, timeout=10)
            if node is not None and node.node_id != self.node_id:
                return {"spill": node.address}
            return {"infeasible": True,
                    "reason": "no node in this job's virtual cluster "
                              "can satisfy the request"}

        runtime_env = payload.get("runtime_env")
        deps = payload.get("deps") or ()
        env_key = renv.env_key(runtime_env)
        if runtime_env:
            await self._ensure_runtime_env(runtime_env)
        if pg_key is not None:
            bundle = self._bundles.get(pg_key)
            if deps and bundle is not None and \
                    _bundle_fits(bundle, demand):
                # Pull-before-grant (ref: LeaseDependencyManager,
                # src/ray/raylet/lease_dependency_manager.h): the
                # bundle is reserved here with enough capacity, so the
                # lease WILL be served on this node — pull the first
                # queued task's plasma args before a worker is
                # selected.  Awaiting mid-selection would race another
                # lease onto the same idle worker; no resources are
                # held during this wait, so a dep produced by a task
                # that needs this node can still schedule here.  (A
                # bundle removed/undersized skips the prefetch — the
                # loop below replies infeasible without paying for a
                # transfer first.)
                await self._prefetch_deps(deps)
            # Lease against a committed placement-group bundle: resources
            # come out of the reservation, never the general pool.
            while True:
                bundle = self._bundles.get(pg_key)
                if bundle is not None and not _bundle_fits(bundle,
                                                           demand):
                    return {"infeasible": True,
                            "reason": f"demand {demand} exceeds bundle "
                                      f"capacity {bundle['resources']}"}
                if self._bundle_can_allocate(pg_key, demand):
                    worker = self._idle_worker(env_key)
                    pool = self._pool_size() + self._starting_workers
                    if worker is None and pool >= self._max_workers + 4:
                        self._retire_idle_mismatch(env_key)
                    if worker is None and pool < self._max_workers + 4:
                        self._starting_workers += 1
                        try:
                            handle = self._spawn_worker(
                                runtime_env=runtime_env)
                            await handle.registered.wait()
                        finally:
                            self._starting_workers -= 1
                        worker = handle if handle.state == IDLE else None
                    if worker is not None:
                        self._bundle_allocate(pg_key, demand)
                        worker.state = LEASED
                        worker.lease_resources = dict(demand)
                        worker.lease_pg = pg_key
                        worker.lease_owner = payload.get("owner") or ""
                        worker.job_id = job_id
                        return {"granted": worker.address,
                                "worker_id": worker.worker_id}
                elif pg_key not in self._bundles:
                    return {"infeasible": True,
                            "reason": "bundle not reserved on this node"}
                self._lease_event.clear()
                try:
                    await asyncio.wait_for(self._lease_event.wait(),
                                           timeout=0.2)
                except asyncio.TimeoutError:
                    pass

        if self._disk_full or self._draining:
            what = ("draining (announced departure)" if self._draining
                    else "out of disk")
            if pinned_here:
                return {"infeasible": True,
                        "reason": f"node-affinity target is {what}"}
            # Redirect rather than accept work this node can't keep:
            # out-of-disk nodes lack spill/log space (ref:
            # file_system_monitor.h), draining nodes are about to die
            # (a lease granted now would be killed mid-task).
            node = await gcs.call_async(
                "SelectNode", {"resources": demand, "job_id": job_id,
                               "exclude": self.node_id,
                               "label_selector": selector}, timeout=10)
            if node is not None and node.node_id != self.node_id:
                return {"spill": node.address}
            return {"infeasible": True,
                    "reason": f"node {what} and no alternative "
                              "node can satisfy the request"}

        if not self._feasible(demand):
            if pinned_here:
                return {"infeasible": True,
                        "reason": f"node-affinity target can never "
                                  f"satisfy {demand}"}
            node = await gcs.call_async(
                "SelectNode", {"resources": demand, "job_id": job_id,
                               "exclude": self.node_id,
                               "label_selector": selector},
                timeout=10)
            if node is not None:
                return {"spill": node.address}
            return {"infeasible": True}

        if deps:
            # Pull-before-grant for the normal path — AFTER the
            # disk-full and feasibility redirects: a node about to
            # spill the lease elsewhere must not absorb the args'
            # write pressure first.
            await self._prefetch_deps(deps)
        start = time.monotonic()
        spill_deadline = start + global_config().spillback_timeout_s
        while True:
            if self._can_allocate(demand):
                worker = self._idle_worker(env_key)
                pool = self._pool_size() + self._starting_workers
                if worker is None and pool >= self._max_workers:
                    self._retire_idle_mismatch(env_key)
                if worker is None and pool < self._max_workers:
                    self._starting_workers += 1
                    try:
                        handle = self._spawn_worker(
                            runtime_env=runtime_env)
                        await handle.registered.wait()
                    finally:
                        self._starting_workers -= 1
                    worker = handle if handle.state == IDLE else None
                if worker is not None:
                    self._allocate(demand)
                    worker.state = LEASED
                    worker.lease_resources = dict(demand)
                    worker.lease_owner = payload.get("owner") or ""
                    worker.job_id = job_id
                    reply = {"granted": worker.address,
                             "worker_id": worker.worker_id}
                    extra = self._grant_extras(payload, demand, env_key,
                                               job_id)
                    if extra:
                        reply["extra"] = extra
                    return reply
            elif not pinned_here and time.monotonic() > spill_deadline:
                node = await gcs.call_async(
                    "SelectNode",
                    {"resources": demand, "job_id": job_id,
                     "exclude": self.node_id,
                     "label_selector": selector,
                     # A saturated SPREAD lease keeps spreading; routing
                     # it with the default packer would concentrate it.
                     "strategy": ("SPREAD" if strategy == "SPREAD"
                                  else None)},
                    timeout=10)
                if node is not None and node.node_id != self.node_id:
                    return {"spill": node.address}
                spill_deadline = time.monotonic() + \
                    global_config().spillback_timeout_s
            self._lease_event.clear()
            try:
                await asyncio.wait_for(self._lease_event.wait(), timeout=0.2)
            except asyncio.TimeoutError:
                pass

    def _grant_extras(self, payload, demand, env_key: str,
                      job_id) -> list[dict]:
        """Batched lease (payload ``count``): after the primary grant,
        hand out up to count-1 MORE leases from capacity that is free
        RIGHT NOW (already-idle workers; never spawns, never waits) so
        a burst of N queued tasks costs one daemon round trip instead
        of N.  Grants the client's queue has drained past come straight
        back via ReturnWorker (core._acquire_worker), so over-granting
        idle capacity is cheap; under-granting just falls back to the
        classic lease-per-round-trip cadence for the remainder."""
        extras: list[dict] = []
        want = int(payload.get("count", 1)) - 1
        while len(extras) < want and self._can_allocate(demand):
            worker = self._idle_worker(env_key)
            if worker is None:
                break
            self._allocate(demand)
            worker.state = LEASED
            worker.lease_resources = dict(demand)
            worker.lease_owner = payload.get("owner") or ""
            worker.job_id = job_id
            extras.append({"granted": worker.address,
                           "worker_id": worker.worker_id})
        return extras

    async def _return_worker(self, payload):
        handle = self._workers.get(payload["worker_id"])
        if handle is None:
            return False
        if handle.state == LEASED:
            if not handle.blocked:
                if handle.lease_pg is not None:
                    self._bundle_release(handle.lease_pg,
                                         handle.lease_resources)
                else:
                    self._release(handle.lease_resources)
            handle.blocked = False
            handle.lease_resources = {}
            handle.lease_pg = None
            handle.lease_owner = ""
            handle.state = IDLE
            self._lease_event.set()
        return True

    def _sweep_lease_owners(self, now: float) -> None:
        """Periodic lessee liveness check for owners NOT on this node
        (drivers, remote workers): a dead owner's lease can't be
        reclaimed by the local worker-death path above.  Interval and
        strike budget come from config
        (lease_owner_sweep_interval_s / lease_owner_ping_strikes)."""
        cfg = global_config()
        if now - getattr(self, "_last_owner_sweep", 0.0) < \
                cfg.lease_owner_sweep_interval_s or \
                getattr(self, "_owner_sweep_running", False):
            return
        self._last_owner_sweep = now
        local = {h.address for h in self._workers.values() if h.address}
        owners = {h.lease_owner for h in self._workers.values()
                  if h.state == LEASED and h.lease_owner
                  and h.lease_owner not in local}
        if not owners:
            return
        strikes_needed = max(1, cfg.lease_owner_ping_strikes)

        fails: dict = getattr(self, "_owner_ping_fails", None)
        if fails is None:
            fails = self._owner_ping_fails = {}
        for stale in [a for a in fails if a not in owners]:
            del fails[stale]   # else a later re-lease inherits old strikes

        async def _sweep():
            self._owner_sweep_running = True
            alive_hosts = None      # fetched at most once per sweep
            try:
                for addr in owners:
                    try:
                        await self._clients.get(addr).call_async(
                            "Ping", {}, timeout=5)
                        fails.pop(addr, None)
                    except (RpcConnectionError, RpcTimeoutError):
                        # Both refusals and black holes (established
                        # connection, no reply) count — but a LOADED
                        # owner on a saturated host can miss pings for
                        # many seconds, and a false reclaim terminates
                        # its busy workers; demand N consecutive
                        # strikes before even considering a reclaim.
                        fails[addr] = fails.get(addr, 0) + 1
                        if fails[addr] < strikes_needed:
                            continue
                        if fails[addr] < strikes_needed * 3:
                            if alive_hosts is None:
                                alive_hosts = \
                                    await self._gcs_alive_hosts()
                            if addr.rsplit(":", 1)[0] in alive_hosts:
                                # The GCS still hears heartbeats from
                                # the owner's node — likely a partition
                                # (or a stalled io loop) between THIS
                                # daemon and the owner, not a death.
                                # Defer, but only up to 3x the strike
                                # budget: node liveness says nothing
                                # about the owner PROCESS, and a dead
                                # driver on a live node must not pin
                                # leases forever.
                                logger.warning(
                                    "lease owner %s unresponsive for "
                                    "%d pings but its node is alive "
                                    "per GCS; deferring reclaim",
                                    addr, fails[addr])
                                continue
                        fails.pop(addr, None)
                        self._reclaim_leases_of(addr)
                    except Exception:  # noqa: BLE001 — reachable but
                        fails.pop(addr, None)  # erroring owner is alive
            finally:
                self._owner_sweep_running = False

        _spawn(_sweep())

    async def _gcs_alive_hosts(self) -> set:
        """Host IPs of nodes the GCS currently believes alive — the
        corroboration set for suspected-dead lease owners (an owner
        process lives on some node, and that node's daemon heartbeats
        the GCS independently of our ping path).  One RPC per sweep:
        during a real partition EVERY remote owner fails pings at
        once, and per-owner refetches would serialize 5s-timeout calls
        against an already-struggling GCS.  Empty set when the GCS
        can't confirm — then we lean toward reclaiming (a dead owner's
        leases must not pin resources forever; the GCS-down case
        fail-stops this daemon anyway via gcs_dead_exit_s)."""
        try:
            gcs = self._clients.get(self._gcs_address)
            infos = await gcs.call_async("GetAllNodes", {}, timeout=5)
        except Exception:  # noqa: BLE001 — GCS unreachable: no veto
            return set()
        return {getattr(info, "address", "").rsplit(":", 1)[0]
                for info in (infos or {}).values()
                if getattr(info, "alive", False)}

    def _reclaim_leases_of(self, owner_address: str) -> None:
        """Reclaim leases whose lessee died (ref: the raylet cancels
        leases on owner death — a dead owner can never send
        ReturnWorker, so its leases would pin resources forever; this
        is exactly the data-ingest leak where a killed train worker's
        read-task lease pool held CPUs for the rest of the session)."""
        if not owner_address:
            return
        for h in list(self._workers.values()):
            if h.state != LEASED or h.lease_owner != owner_address:
                continue
            logger.info("reclaiming lease of worker %s: owner %s died",
                        h.worker_id.hex()[:8], owner_address)
            if not h.blocked:
                if h.lease_pg is not None:
                    self._bundle_release(h.lease_pg, h.lease_resources)
                else:
                    self._release(h.lease_resources)
            h.blocked = False
            h.lease_resources = {}
            h.lease_pg = None
            h.lease_owner = ""
            # The worker may still be executing (or wedged on) the dead
            # owner's task — terminate rather than re-lease a busy
            # process (the monitor loop reaps the handle; the pool
            # respawns on demand).
            self._terminate_worker(h)
        self._lease_event.set()

    async def _worker_blocked(self, payload):
        """Worker blocked in get(): release its cpu so nested tasks can run
        (ref: raylet releases resources for blocked workers).  Applies to
        ACTOR workers too — a worker-group of actors that all block in
        get() must not starve the tasks they are waiting on (the
        data-ingest deadlock: train workers hold every CPU while the
        dataset's read tasks wait for one)."""
        handle = self._workers.get(payload["worker_id"])
        if handle is None or handle.blocked:
            return True
        if handle.state == LEASED:
            handle.blocked = True
            if handle.lease_pg is not None:
                self._bundle_release(handle.lease_pg, handle.lease_resources)
            else:
                self._release(handle.lease_resources)
        elif handle.state == ACTOR and handle.actor_spec is not None:
            handle.blocked = True
            self._release_actor_resources(handle.actor_spec)
        return True

    async def _worker_unblocked(self, payload):
        handle = self._workers.get(payload["worker_id"])
        if handle is None or not handle.blocked:
            return True
        # Re-acquire even if it drives availability negative: the worker
        # already holds the lease; balance restores at return.
        if handle.state == LEASED:
            handle.blocked = False
            if handle.lease_pg is not None:
                self._bundle_allocate(handle.lease_pg,
                                      handle.lease_resources)
            else:
                self._allocate(handle.lease_resources)
        elif handle.state == ACTOR and handle.actor_spec is not None:
            handle.blocked = False
            spec = handle.actor_spec
            if spec.placement_group_id is not None:
                self._bundle_allocate(
                    (spec.placement_group_id,
                     spec.placement_group_bundle_index), spec.resources)
            else:
                self._allocate(spec.resources)
        return True

    # ------------------------------------------------------------ bundles
    # 2-phase placement-group reservation (ref: raylet
    # placement_group_resource_manager.h prepare/commit/return)

    async def _prepare_bundle(self, payload):
        key = (payload["pg_id"], payload["index"])
        if key in self._bundles:
            return {"ok": True}  # idempotent retry
        resources = payload["resources"]
        if not self._can_allocate(resources):
            return {"ok": False, "reason": "insufficient resources"}
        self._allocate(resources)
        self._bundles[key] = {
            "resources": dict(resources),
            "available": dict(resources),
            "committed": False,
        }
        return {"ok": True}

    async def _commit_bundle(self, payload):
        key = (payload["pg_id"], payload["index"])
        bundle = self._bundles.get(key)
        if bundle is None:
            return {"ok": False}
        bundle["committed"] = True
        return {"ok": True}

    async def _return_bundle(self, payload):
        key = (payload["pg_id"], payload["index"])
        bundle = self._bundles.pop(key, None)
        if bundle is not None:
            # Release only the unused portion now; leases still running
            # against this bundle return their share to the general pool
            # when they finish (see _bundle_release) — otherwise removal
            # would oversubscribe the node while tasks still run.
            self._release(bundle["available"])
        return True

    def _bundle_can_allocate(self, key, demand) -> bool:
        bundle = self._bundles.get(key)
        return bundle is not None and bundle["committed"] and all(
            bundle["available"].get(k, 0.0) >= v for k, v in demand.items())

    def _bundle_allocate(self, key, demand):
        bundle = self._bundles.get(key)
        if bundle is None:
            # Bundle returned/removed while the holder was blocked: its
            # (released) share went back to the general pool with the
            # bundle, so re-acquire from the pool (mirror of the
            # _bundle_release fallback).
            self._allocate(demand)
            return
        for k, v in demand.items():
            bundle["available"][k] = bundle["available"].get(k, 0.0) - v

    def _bundle_release(self, key, demand):
        bundle = self._bundles.get(key)
        if bundle is None:
            # Bundle was removed while this lease was outstanding: its
            # in-use portion was withheld from the general pool at
            # ReturnBundle time, so it goes back to the pool here.
            self._release(demand)
            return
        for k, v in demand.items():
            bundle["available"][k] = bundle["available"].get(k, 0.0) + v
        self._lease_event.set()

    # ------------------------------------------------------------ actors

    async def _start_actor_worker(self, spec: ActorSpec):
        if spec.runtime_env:
            await self._ensure_runtime_env(spec.runtime_env)
        if spec.placement_group_id is not None:
            key = (spec.placement_group_id,
                   spec.placement_group_bundle_index)
            if not self._bundle_can_allocate(key, spec.resources):
                raise RuntimeError("bundle cannot host this actor")
            self._bundle_allocate(key, spec.resources)
            self._spawn_worker(actor_spec=spec)
            return True
        placement = spec.placement_resources or spec.resources
        if not self._feasible(placement):
            raise RuntimeError("insufficient node resources for actor")
        # Only the running demand is held for the actor's lifetime
        # (placement demand is a scheduling-time constraint).
        self._allocate(spec.resources)
        self._spawn_worker(actor_spec=spec)
        return True

    async def _kill_actor_worker(self, payload):
        actor_id = payload["actor_id"]
        for handle in list(self._workers.values()):
            if handle.actor_spec is not None and \
                    handle.actor_spec.actor_id == actor_id:
                # Clear the spec first so the monitor loop doesn't report
                # an (expected) death to the GCS.
                spec = handle.actor_spec
                handle.actor_spec = None
                handle.state = STARTING
                if not handle.blocked:  # blocked already released
                    self._release_actor_resources(spec)
                handle.blocked = False
                self._terminate_worker(handle)
                return True
        return False

    def _release_actor_resources(self, spec: ActorSpec):
        if spec.placement_group_id is not None:
            self._bundle_release(
                (spec.placement_group_id,
                 spec.placement_group_bundle_index), spec.resources)
        else:
            self._release(spec.resources)

    # ------------------------------------------------------------ objects

    async def _seal_object(self, payload):
        """A colocated process wrote `<store_dir>/<hex>.tmp.<nonce>`; rename
        into place and account for it."""
        object_id: ObjectID = payload["object_id"]
        final = self.store.seal_file(object_id, payload["tmp_path"])
        gcs = self._clients.get(self._gcs_address)
        await gcs.call_async(
            "ObjectLocationAdd",
            self._location_add_payload(object_id, payload), timeout=10)
        return {"path": final}

    def _location_add_payload(self, object_id: ObjectID,
                              seal_payload: dict) -> dict:
        """Directory registration for a freshly SEALED object — the
        producer's attribution (owner address, optional creation
        callsite) rides along so `art memory` can say who made it."""
        out = {"object_id": object_id, "node_id": self.node_id}
        if seal_payload.get("owner"):
            out["owner"] = seal_payload["owner"]
        if seal_payload.get("callsite"):
            out["callsite"] = seal_payload["callsite"]
        return out

    async def _create_buffer(self, payload):
        """Grant a colocated producer a write window in the arena
        (plasma create→seal protocol; ref: CreateRequestQueue)."""
        from ant_ray_tpu._private.object_store import BufferExistsError  # noqa: PLC0415

        if not self.store.uses_arena:
            return {"unsupported": True}
        object_id = payload["object_id"]
        try:
            offset = self.store.create_buffer(object_id, payload["size"])
        except BufferExistsError as e:
            if e.sealed:
                return {"exists": True}
            # An unsealed grant may belong to a live producer (or to our
            # own in-flight pull) still writing through its view — only
            # reclaim it once it has gone stale (crashed producer).
            ttl = global_config().unsealed_grant_ttl_s
            if self.store.grant_age(object_id) < ttl:
                return {"busy": True}
            self.store.abort_buffer(object_id)
            try:
                offset = self.store.create_buffer(object_id,
                                                  payload["size"])
            except BufferExistsError as e2:
                return {"exists": True} if e2.sealed else {"busy": True}
        return {"path": self.store.arena_path,
                "offset": self.store.arena_file_offset(offset)}

    async def _seal_buffer(self, payload):
        object_id = payload["object_id"]
        self.store.seal_buffer(object_id)
        gcs = self._clients.get(self._gcs_address)
        await gcs.call_async(
            "ObjectLocationAdd",
            self._location_add_payload(object_id, payload), timeout=10)
        return True

    # Hard cap on any single pin lease: a misconfigured client can't
    # wedge an arena slot forever — live readers renew well inside this,
    # so only crashed readers ever hit it.
    _MAX_PIN_LEASE_S = 3600.0

    def _pin_lease_s(self, ttl: float | None) -> float:
        return min(max(ttl or 0.0, global_config().read_pin_ttl_s),
                   self._MAX_PIN_LEASE_S)

    def _locate_pinned(self, object_id: ObjectID,
                       ttl: float | None = None) -> dict | None:
        """Locate for a reader, pinning arena entries until the client's
        ReadDone — eviction reuses arena slots, so an unpinned window
        could be recycled mid-copy.  Each pin carries a lease so a
        reader that dies before ReadDone can't wedge the slot forever
        (the heartbeat loop reaps expired leases).  Zero-copy readers
        pass a longer ``ttl`` since they hold the window for the
        lifetime of the deserialized value, not just a memcpy, and
        renew it via RenewPins heartbeats."""
        located = self.store.locate(object_id)
        if located is not None and located["offset"] is not None:
            token = self._next_pin_token
            self._next_pin_token += 1
            self.store.pin(object_id, token)
            self._pin_leases.setdefault(object_id, {})[token] = (
                time.monotonic() + self._pin_lease_s(ttl))
            located["pinned"] = True
            located["pin_token"] = token
        return located

    async def _read_done(self, payload):
        object_id = payload["object_id"]
        leases = self._pin_leases.get(object_id)
        if not leases:
            return True
        token = payload.get("pin_token")
        if token is None:
            # Legacy caller without a token: drop the earliest-expiring
            # lease (best effort).
            token = min(leases, key=leases.get)
        if leases.pop(token, None) is not None:
            if not leases:
                self._pin_leases.pop(object_id, None)
            self.store.unpin(object_id, token)
        return True

    async def _renew_pins(self, payload):
        """Batch-extend live readers' pin leases (one client heartbeat
        renews every pin that client still holds).  Renewal instead of
        an unbounded TTL keeps the reap loop able to reclaim pins of
        crashed readers within ~one TTL.  Replies with the (oid, token)
        pairs that no longer exist so the client can scream — a gone
        pin under a live value means its bytes may be recycled."""
        ttl = self._pin_lease_s(payload.get("ttl"))
        expiry = time.monotonic() + ttl
        gone = []
        for oid, token in payload["pins"]:
            leases = self._pin_leases.get(oid)
            if leases is None or token not in leases:
                gone.append((oid, token))
            else:
                leases[token] = expiry
        return {"gone": gone}

    def _reap_expired_pins(self):
        now = time.monotonic()
        for object_id in list(self._pin_leases):
            leases = self._pin_leases[object_id]
            for token, expiry in list(leases.items()):
                if expiry < now:
                    del leases[token]
                    self.store.unpin(object_id, token)
                    logger.warning(
                        "read pin on %s expired without ReadDone",
                        object_id.hex()[:8])
            if not leases:
                self._pin_leases.pop(object_id, None)

    async def _locate_object(self, payload):
        located = self.store.locate(payload["object_id"])
        if located is not None:
            # Transfer-source probes learn the bulk data channel here
            # (additive key; colocated readers ignore it).
            located["bulk_port"] = self._bulk_port
        return located

    async def _contains_object(self, payload):
        return self.store.contains(payload["object_id"])

    async def _prefetch_deps(self, deps) -> None:
        """Pull a pending lease's plasma args node-local before grant
        (ref: lease_dependency_manager.h — pull-before-grant).  Bounded
        by lease_dep_prefetch_timeout_s: a missing or slow dep delays
        the grant at most that long; the executing worker's own fetch
        stays the authority either way.  Concurrent leases of one
        scheduling key all carry the head task's deps, so per-object
        pulls coalesce node-wide — N parallel leases cost ONE transfer,
        not N.  Tracked in sync_stats for tests/observability."""
        budget = global_config().lease_dep_prefetch_timeout_s
        if budget <= 0:
            return
        await asyncio.gather(
            *[self._coalesced_prefetch(oid, budget) for oid in deps])

    def _coalesced_prefetch(self, oid, budget: float):
        task = self._prefetching.get(oid)
        if task is None or task.done():
            task = asyncio.ensure_future(self._prefetch_one(oid, budget))
            self._prefetching[oid] = task
            task.add_done_callback(
                lambda _t, o=oid: (self._prefetching.pop(o, None)
                                   if self._prefetching.get(o) is _t
                                   else None))
        return asyncio.shield(task)

    async def _prefetch_one(self, oid, budget: float) -> None:
        try:
            reply = await self._ensure_local(
                {"object_id": oid, "timeout": budget, "prefetch": True,
                 # A dep with no holders yet (producer still running,
                 # or eviction raced us) stops costing grant latency
                 # quickly — the worker's own fetch is the authority.
                 # Same knob as worker-side fetches so one setting
                 # tunes the whole no-holders policy.
                 "fail_fast_after": min(
                     global_config().pull_no_holders_grace_s, budget)})
            if reply.get("ok"):
                self.sync_stats["dep_prefetches"] = (
                    self.sync_stats.get("dep_prefetches", 0) + 1)
        except Exception:  # noqa: BLE001 — prefetch is best-effort
            pass

    async def _ensure_local(self, payload):
        """Make the object local (pull from a holder if needed); reply
        path (ref: PullManager, pull_manager.h:50).  Traced pulls
        (payload ``trace``) record a ``daemon:object_pull`` child span
        with the pulled size — the wire/queue decomposition of a slow
        ``get()`` lands in the request's trace."""
        wire = payload.get("trace")
        if wire is None:
            return await self._ensure_local_impl(payload)
        from ant_ray_tpu.observability import tracing_plane  # noqa: PLC0415

        with tracing_plane.server_span(wire, "daemon:object_pull",
                                       "EnsureLocal") as sp:
            sp.attrs = {"object_id": payload["object_id"].hex()}
            reply = await self._ensure_local_impl(payload)
            sp.attrs["size"] = reply.get("size")
            sp.error = "no_holders" in reply or "timeout" in reply
            return reply

    async def _ensure_local_impl(self, payload):
        object_id: ObjectID = payload["object_id"]
        prefetch = payload.get("prefetch", False)
        deadline = time.monotonic() + payload.get("timeout", 60.0)
        # After this many seconds of continuously-empty holder lists the
        # request fails fast with {"no_holders"} so the owner can start
        # lineage reconstruction instead of burning the full timeout
        # (ref: ObjectRecoveryManager, object_recovery_manager.h:98).
        fail_fast_after = payload.get("fail_fast_after")
        pin_ttl = payload.get("pin_ttl")

        def _locate():
            # Prefetch (lease dependency pulls) wants locality only —
            # taking a read pin would wedge the slot until a ReadDone
            # nobody will ever send.
            if prefetch:
                return ({"ok": True} if self.store.contains(object_id)
                        else None)
            return self._locate_pinned(object_id, pin_ttl)

        no_holders_since: float | None = None
        located = _locate()
        if located is not None:
            return located
        gcs = self._clients.get(self._gcs_address)
        pull_failures = 0
        while time.monotonic() < deadline:
            # A colocated producer (or a concurrent EnsureLocal) may have
            # sealed the object since the last iteration.
            located = _locate()
            if located is not None:
                return located
            holders: list[NodeInfo] = await gcs.call_async(
                "ObjectLocationsGet", {"object_id": object_id}, timeout=10)
            holders = [h for h in holders if h.node_id != self.node_id]
            # Randomized holder order spreads a broadcast across every
            # node that already completed its pull, instead of every
            # puller hammering the first-listed holder.  (The stripe
            # planner re-sorts deterministically; randomization still
            # picks WHICH holder serves a small, unstriped object.)
            random.shuffle(holders)
            viable = False
            # A round with NO reachable copy feeds the fail-fast clock
            # only when every listed holder verifiably lost the object
            # (retracted) — a merely-unreachable holder (restarting RPC
            # server, short partition) must not fast-track the owner
            # into lineage reconstruction.
            holderless = not holders
            if holders:
                try:
                    await self._pull_object(object_id, holders)
                    viable = True
                    pull_failures = 0
                except _NoViableHolder as e:
                    # Stale misses were retracted inside _pull_object,
                    # so the NEXT GCS round already sees an honest
                    # list — re-locate immediately.
                    holderless = not e.any_unreachable
                except Exception as e:  # noqa: BLE001 — transient pull
                    # A viable holder existed but the transfer failed
                    # mid-flight (holder death, concurrent grant): the
                    # holder list is refreshed right away; back off only
                    # on CONSECUTIVE failures so one dead holder never
                    # costs a 50 ms sleep while live ones remain.
                    logger.debug("pull of %s failed: %s",
                                 object_id.hex()[:8], e)
                    viable = True
                    pull_failures += 1
                    if pull_failures > 1:
                        await asyncio.sleep(
                            min(0.02 * pull_failures, 0.5))
            if viable:
                no_holders_since = None
                located = _locate()
                if located is not None:
                    await gcs.call_async("ObjectLocationAdd", {
                        "object_id": object_id,
                        "node_id": self.node_id}, timeout=10)
                    return located
                continue
            # Full round with no viable holder: fail-fast bookkeeping
            # (true holderless rounds only) and the (only) inter-round
            # sleep.  A locally-spilled (or mid-produce) object never
            # feeds the clock: the holder list excludes THIS node, so
            # on a single-holder node every round is "holderless" even
            # while the payload sits in the local spill dir — and a
            # transiently-failing restore (store full of pinned
            # entries) would otherwise escalate into a terminal
            # "no holders" verdict on an object that provably exists.
            if not holderless or self.store.contains(object_id):
                no_holders_since = None
            elif fail_fast_after is not None:
                now = time.monotonic()
                if no_holders_since is None:
                    no_holders_since = now
                elif now - no_holders_since >= fail_fast_after:
                    located = _locate()
                    return located if located is not None else {
                        "no_holders": True}
            await asyncio.sleep(0.05)
        return {"timeout": True}

    async def _pull_object(self, object_id: ObjectID, holders):
        """One pull attempt: probe the listed holders (concurrently, one
        RTT), retract stale locations, then stream the object in with
        the windowed/striped chunk scheduler.  Quota accounts the whole
        object size ONCE — stripes share the object's admission, they
        are not independent transfers."""
        gcs = self._clients.get(self._gcs_address)

        async def probe(holder):
            try:
                info = await self._clients.get(holder.address).call_async(
                    "LocateObject", {"object_id": object_id}, timeout=10)
            except Exception:  # noqa: BLE001 — unreachable holder
                return holder, -1
            return holder, info

        live, size, bulk_ports = [], None, {}
        any_unreachable = False

        async def absorb(holder, info) -> None:
            nonlocal size, any_unreachable
            if info is None:
                # Stale location (holder evicted it): retract so the
                # next round sees an honest holder list.
                await gcs.oneway_async("ObjectLocationRemove", {
                    "object_id": object_id, "node_id": holder.node_id})
            elif info == -1:
                any_unreachable = True
            else:
                live.append(holder)
                size = info["size"]
                bulk_ports[holder.node_id] = info.get("bulk_port")

        # Probe SEQUENTIALLY until one holder answers (the common
        # broadcast of a small object costs ONE probe per puller, like
        # the old path — not O(holders), which would make an N-node
        # broadcast O(N^2) control RPCs cluster-wide)...
        remaining = list(holders)
        while remaining and not live:
            holder = remaining.pop(0)
            await absorb(*(await probe(holder)))
        if not live:
            raise _NoViableHolder(object_id.hex()[:12], any_unreachable)
        # ...and fan the rest out concurrently ONLY when the size makes
        # striping possible and extra holders would add NIC lanes.
        stripe_min = global_config().object_stripe_min_bytes
        if remaining and stripe_min > 0 and size >= stripe_min:
            for holder, info in await asyncio.gather(
                    *[probe(h) for h in remaining]):
                await absorb(holder, info)
        await self._acquire_pull_quota(size)
        try:
            await self._pull_body(object_id, size, live, bulk_ports)
        finally:
            await self._release_pull_quota(size)

    async def _acquire_pull_quota(self, size: int):
        """Admission control on inbound transfer bytes (ref:
        pull_manager.h:50 pull quota): a burst of pulls bigger than the
        quota queues here instead of over-committing store memory."""
        quota = global_config().pull_quota_bytes
        if quota <= 0:
            return
        async with self._pull_quota_cv:
            if self._pull_bytes_inflight > 0 and \
                    self._pull_bytes_inflight + size > quota:
                self.transfer_stats["quota_waits"] += 1
            while (self._pull_bytes_inflight > 0
                   and self._pull_bytes_inflight + size > quota):
                await self._pull_quota_cv.wait()
            self._pull_bytes_inflight += size

    async def _release_pull_quota(self, size: int):
        if global_config().pull_quota_bytes <= 0:
            return
        async with self._pull_quota_cv:
            self._pull_bytes_inflight -= size
            self._pull_quota_cv.notify_all()

    async def _pull_body(self, object_id: ObjectID, size: int, live,
                         bulk_ports):
        """Create the local grant and stream the payload in; chunks land
        position-addressed (out of order), so the write sink is a
        random-access memoryview for both backends (bulk pumps
        ``recv_into`` socket bytes straight into it)."""
        if self.store.uses_arena:
            from ant_ray_tpu._private.object_store import BufferExistsError  # noqa: PLC0415

            try:
                self.store.create_buffer(object_id, size)
            except BufferExistsError as e:
                if e.sealed:
                    return  # already local — nothing to pull
                # Another coroutine's pull (or a local producer) owns the
                # grant; let the caller's retry loop re-check presence.
                raise RuntimeError(
                    "concurrent write in progress for this object") from e
            try:
                view = self.store.view_unsealed(object_id)

                def view_at(off, n):
                    return view[off:off + n]

                await self._pull_chunks(object_id, size, live,
                                        bulk_ports, view_at)
            except BaseException:
                # Includes CancelledError at shutdown: never leave a
                # wedged half-written grant (we created it above, so it
                # is ours to abort).
                self.store.abort_buffer(object_id)
                raise
            self.store.seal_buffer(object_id)
            return
        tmp = self.store.path_of(object_id) + ".pull"
        try:
            with open(tmp, "w+b") as f:
                if size > 0:
                    import mmap  # noqa: PLC0415

                    f.truncate(size)
                    m = mmap.mmap(f.fileno(), size)
                    view = memoryview(m)

                    def view_at(off, n):
                        return view[off:off + n]

                    await self._pull_chunks(object_id, size, live,
                                            bulk_ports, view_at)
                    m.flush()
                    # No explicit close: a straggler pump thread may
                    # still hold a slice; GC reclaims the mapping once
                    # the last view dies (the file itself is renamed by
                    # seal_file below, which mmaps don't mind).
        except BaseException:
            try:
                os.unlink(tmp)
            except FileNotFoundError:
                pass
            raise
        self.store.seal_file(object_id, tmp)

    async def _pull_chunks(self, object_id: ObjectID, size: int, live,
                           bulk_ports, view_at):
        """Streaming chunk scheduler (ref: PushManager windowed chunking,
        push_manager.h:28, redesigned pull-side).

        * **Windowed pipelining** — each holder pump keeps up to
          ``object_pull_window`` chunk requests in flight, so
          throughput is bounded by the wire, not chunk_size/RTT.
        * **Bulk data channel** — holders that advertise a bulk port
          are drained by a blocking-socket worker thread
          (transfer.pull_chunks) that ``recv_into``-s replies straight
          into the grant view: socket → shared memory with no event
          loop or pickle on the hot path.  Holders without one (older
          peers) fall back to windowed ReadChunkRaw RPCs.
        * **Multi-holder striping** — past ``object_stripe_min_bytes``
          with >=2 live holders, the chunk range is partitioned into
          contiguous per-holder stripes pulled concurrently into the
          same grant.  Holder order is DETERMINISTIC (node-id sort) so
          every puller in a broadcast assigns the same stripe to the
          same holder — each holder's chunk cache then serves exactly
          its stripe and the read-each-chunk-once property survives.
        * **Failover without re-pull** — a dying pump returns every
          chunk it did not complete to a shared overflow queue; live
          pumps drain it, and if none remain a spare/finished holder is
          respawned.  No completed byte is ever transferred twice.
        """
        import threading  # noqa: PLC0415

        from ant_ray_tpu._private import transfer  # noqa: PLC0415

        cfg = global_config()
        chunk = cfg.object_transfer_chunk_size
        window = max(1, cfg.object_pull_window)
        offsets = list(range(0, size, chunk))
        striped = (cfg.object_stripe_min_bytes > 0
                   and size >= cfg.object_stripe_min_bytes
                   and len(live) >= 2 and len(offsets) >= 2)
        if striped:
            # Deterministic stripe-to-holder assignment (see docstring).
            # Unstriped pulls keep the caller's shuffled order — the
            # shuffle is what spreads a small-object broadcast across
            # holders instead of hammering the lowest node id.
            live = sorted(live, key=lambda h: h.node_id.hex())
        k = len(live) if striped else 1
        share = (len(offsets) + k - 1) // k
        owns = [deque(offsets[i * share:(i + 1) * share])
                for i in range(k)]
        overflow: deque = deque()
        spares = deque(live[k:])
        stop = threading.Event()
        if striped:
            self.transfer_stats["stripe_pulls"] += 1

        def make_take(own: deque):
            def take():
                if stop.is_set():
                    return None
                # try/except, not check-then-pop: the overflow deque is
                # shared across pump threads and the io loop.
                try:
                    return own.popleft()
                except IndexError:
                    pass
                try:
                    return overflow.popleft()
                except IndexError:
                    return None
            return take

        async def bulk_pump(holder, own: deque, port: int):
            host = holder.address.rsplit(":", 1)[0]
            progress = [0]            # single writer: the pump thread
            fut = asyncio.get_running_loop().run_in_executor(
                None, transfer.pull_chunks, (host, port), object_id,
                size, chunk, window, make_take(own), overflow.append,
                view_at, striped, progress)
            try:
                await asyncio.shield(fut)
            except asyncio.CancelledError:
                # The worker thread cannot be cancelled; tell it to stop
                # taking chunks and reap it so no writer outlives the
                # grant this coroutine's caller is about to abort.
                stop.set()
                try:
                    await fut
                except Exception:  # noqa: BLE001 — already cancelling
                    pass
                raise
            except transfer.BulkMiss as e:
                raise _HolderMiss(str(e)) from e
            finally:
                # Tallied HERE (io loop), success AND failure paths —
                # chunks a dying holder already delivered stay written
                # (never re-pulled), so they must stay counted.  Skip
                # only if the thread still runs (double-cancel); its
                # write would race the read.
                if fut.done():
                    self.transfer_stats["pull_bytes"] += progress[0]
                    self.transfer_stats["pull_bytes_bulk"] += progress[0]

        async def rpc_pump(holder, own: deque):
            from ant_ray_tpu.exceptions import ObjectLostError  # noqa: PLC0415

            remote = self._clients.get(holder.address)
            take = make_take(own)
            inflight: deque = deque()
            method = "ReadChunkRaw"
            try:
                while True:
                    while len(inflight) < window:
                        off = take()
                        if off is None:
                            break
                        n = min(chunk, size - off)
                        try:
                            fut = await remote.send_request(
                                method,
                                {"object_id": object_id, "offset": off,
                                 "length": n, "stripe": striped})
                        except BaseException:
                            # The taken offset is in neither inflight
                            # nor the queues — requeue before failing.
                            overflow.append(off)
                            raise
                        inflight.append((off, n, fut))
                    if not inflight:
                        return
                    off, n, fut = inflight.popleft()
                    try:
                        data = await asyncio.wait_for(fut, 60)
                    except ObjectLostError:
                        overflow.append(off)
                        raise _HolderMiss(
                            "holder no longer has the object") from None
                    except RpcError as e:
                        overflow.append(off)
                        if "no route" in str(e) and \
                                "ReadChunkRaw" in str(e):
                            # Pre-raw-frame peer: fall back to the
                            # legacy pickled ReadChunk for this holder.
                            # Every already-pipelined raw future fails
                            # the same way and re-enters this branch,
                            # so window > 1 drains cleanly too.
                            method = "ReadChunk"
                            continue
                        raise
                    except BaseException:
                        overflow.append(off)
                        raise
                    if data is None:
                        overflow.append(off)
                        raise _HolderMiss(
                            "holder no longer has the object")
                    if len(data) != n:
                        overflow.append(off)
                        raise RuntimeError(
                            f"short read at {off}/{size} from holder")
                    view_at(off, n)[:] = data
                    self.transfer_stats["pull_bytes"] += n
                    self.transfer_stats["pull_bytes_relayed"] += n
            except BaseException:
                # In-flight chunks go back for survivors — exactly the
                # not-yet-completed remainder, never a re-pulled byte.
                overflow.extend(o for o, _n, _f in inflight)
                raise

        async def pump(holder, own: deque):
            port = bulk_ports.get(holder.node_id)
            try:
                if port:
                    await bulk_pump(holder, own, port)
                else:
                    await rpc_pump(holder, own)
            except BaseException:
                overflow.extend(own)
                own.clear()
                raise

        tasks = {asyncio.ensure_future(pump(live[i], owns[i])): live[i]
                 for i in range(k)}
        healthy: list = []
        last_err: BaseException | None = None
        gcs = self._clients.get(self._gcs_address)
        try:
            while tasks:
                done, _ = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED)
                for t in done:
                    holder = tasks.pop(t)
                    err = t.exception()
                    if err is None:
                        healthy.append(holder)
                        continue
                    last_err = err
                    self.transfer_stats["holder_failures"] += 1
                    if striped and overflow:
                        self.transfer_stats["stripe_failovers"] += 1
                    if isinstance(err, _HolderMiss):
                        await gcs.oneway_async("ObjectLocationRemove", {
                            "object_id": object_id,
                            "node_id": holder.node_id})
                    logger.debug("pull pump for %s on %s failed: %s",
                                 object_id.hex()[:8], holder.address,
                                 err)
                if not tasks and overflow:
                    # Every pump is gone but chunks remain: respawn on a
                    # spare holder, else one that finished its stripe
                    # cleanly (it is alive and still holds the object).
                    nxt = (spares.popleft() if spares
                           else healthy.pop() if healthy else None)
                    if nxt is None:
                        raise last_err or RuntimeError(
                            "pull failed on every holder")
                    tasks[asyncio.ensure_future(pump(nxt, deque()))] = nxt
            if overflow or any(owns):
                raise last_err or RuntimeError(
                    "pull ended with chunks missing")
        except BaseException:
            stop.set()
            for t in tasks:
                t.cancel()
            if tasks:
                # Reap pumps (including their executor threads) BEFORE
                # the caller aborts the grant — a straggler writer must
                # never touch a recycled arena range.
                try:
                    await asyncio.gather(*tasks, return_exceptions=True)
                except asyncio.CancelledError:
                    # Double cancel: a second cancellation landing while
                    # we reap the pumps must not mask the original
                    # failure re-raised below.
                    pass
            raise

    def _on_store_delete(self, object_id: ObjectID):
        """Store eviction hook: retract this node's GCS location record
        so pullers don't chase stale holders (and owners can trigger
        lineage reconstruction promptly).  May fire on any thread."""
        if self._stopping or not self.address:
            return
        try:
            self._io.loop.call_soon_threadsafe(
                self._drop_cached_chunks, object_id)
        except RuntimeError:   # loop closed: teardown eviction
            pass
        try:
            gcs = self._clients.get(self._gcs_address)
            self._io.loop.call_soon_threadsafe(
                _spawn,
                gcs.oneway_async("ObjectLocationRemove", {
                    "object_id": object_id, "node_id": self.node_id}))
        except Exception:  # noqa: BLE001 — best-effort during teardown
            pass

    async def _read_chunk(self, payload):
        """Serve one transfer chunk, memoized: during a broadcast every
        puller asks for the same chunks, so the store is read once per
        chunk and the bytes are shared across repliers (objects are
        immutable while they exist; deletion drops the cache entries)."""
        key = (payload["object_id"], payload["offset"], payload["length"])
        self._chunk_read_log.append((key[0].hex(), key[1], key[2]))
        cached = self.cache_get_chunk(key)
        if cached is not None:
            self._bump_stats(chunk_cache_hits=1)
            return cached
        data = self.store.read_chunk(*key)
        self._bump_stats(chunk_reads=1)
        self.cache_put_chunk(key, data)
        return data

    def _bump_stats(self, **deltas) -> None:
        """Transfer-counter increments under the cache lock — bulk
        handler threads bump the same dict slots concurrently, and +=
        on a dict slot is a read-modify-write."""
        with self._chunk_cache_lock:
            for key, delta in deltas.items():
                self.transfer_stats[key] += delta

    def cache_get_chunk(self, key):
        """LRU chunk-cache lookup (io loop AND bulk threads)."""
        with self._chunk_cache_lock:
            cached = self._chunk_cache.get(key)
            if cached is not None:
                self._chunk_cache.move_to_end(key)
            return cached

    def cache_put_chunk(self, key, data) -> None:
        """Memoize a served chunk under the byte cap (stable copy —
        cache entries must outlive arena slots)."""
        cap = global_config().transfer_chunk_cache_bytes
        if cap <= 0 or len(data) > cap:
            return
        data = bytes(data)
        with self._chunk_cache_lock:
            if key in self._chunk_cache:
                return
            self._chunk_cache[key] = data
            self._chunk_cache_bytes += len(data)
            while self._chunk_cache_bytes > cap:
                _old_key, old = self._chunk_cache.popitem(last=False)
                self._chunk_cache_bytes -= len(old)

    def _read_chunk_raw(self, payload):
        """Zero-copy transfer chunk serving (sync FAST route: the raw
        reply is written before any other io-loop task can run, so an
        arena view is handed straight to the transport — no bytes
        materialization, no pickle round trip).  The chunk cache key
        stays ``(object_id, offset, length)``: striped pulls use the
        same uniform chunk offsets, so stripe reads and broadcast reads
        memoize identically.  Replies ``None`` when the object is gone
        (stale holder — the puller retracts the location)."""
        key = (payload["object_id"], payload["offset"], payload["length"])
        self._chunk_read_log.append((key[0].hex(), key[1], key[2]))
        delay = global_config().testing_chunk_serve_delay_s
        cached = self.cache_get_chunk(key)
        if cached is not None:
            self._bump_stats(chunk_cache_hits=1,
                             **({"stripe_cache_hits": 1}
                                if payload.get("stripe") else {}))
            return (self._delayed_raw(cached, delay) if delay > 0
                    else RawReply(cached))
        # PINNED view, not a bare one: bulk handler threads mutate the
        # store concurrently (restore -> create -> evict), so an
        # unpinned arena window could be recycled before the transport
        # consumes it.  The pin drops via the RawReply release hook
        # right after the write.
        token = ("rawrpc", next(_raw_serve_tokens))
        data = self.store.chunk_view_pinned(*key, token)
        if data is None:
            return None
        self._bump_stats(chunk_reads=1)
        # cache_put_chunk makes its own stable copy under the cap; the
        # reply still serves the live view (zero-copy on this route).
        self.cache_put_chunk(key, data)
        oid = key[0]
        if delay > 0:
            reply = self._delayed_raw(data, delay)
            self.store.unpin(oid, token)   # _delayed_raw copied already
            return reply
        return RawReply(data,
                        release=lambda: self.store.unpin(oid, token))

    def _delayed_raw(self, data, delay: float):
        """Test-only slow serving (testing_chunk_serve_delay_s): resolve
        the reply future after a pause so tests can kill a holder
        mid-transfer deterministically.  The payload is copied — the
        synchronous-write zero-copy guarantee doesn't hold across the
        delay."""
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        data = bytes(data)
        loop.call_later(
            delay,
            lambda: None if fut.done() else fut.set_result(RawReply(data)))
        return fut

    def _drop_cached_chunks(self, object_id: ObjectID) -> None:
        with self._chunk_cache_lock:
            for key in [k for k in self._chunk_cache
                        if k[0] == object_id]:
                self._chunk_cache_bytes -= len(self._chunk_cache.pop(key))

    def _pull_relayed_fraction(self) -> float:
        relayed = self.transfer_stats["pull_bytes_relayed"]
        total = relayed + self.transfer_stats["pull_bytes_bulk"]
        return relayed / total if total else 0.0

    async def _get_transfer_stats(self, payload):
        stats = dict(self.transfer_stats)
        stats["chunk_cache_bytes"] = self._chunk_cache_bytes
        stats["object_pull_relayed_fraction"] = \
            self._pull_relayed_fraction()
        if payload and payload.get("include_read_log"):
            stats["read_log"] = list(self._chunk_read_log)
        return stats

    async def _delete_object(self, payload):
        # GCS-driven delete: its location record is already retracted,
        # so skip the on_delete location-remove echo.
        self._drop_cached_chunks(payload["object_id"])
        self.store.delete(payload["object_id"], notify=False)
        return True


def main():  # pragma: no cover — exercised via subprocess in tests
    import argparse
    import json
    import signal

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--monitor-pid", type=int, default=0,
                        help="exit when this process disappears")
    args = parser.parse_args()

    logging.basicConfig(
        level=global_config().log_level,
        format="[noded %(levelname)s %(asctime)s] %(message)s")
    manager = NodeManager(
        gcs_address=args.gcs_address,
        resources=json.loads(args.resources),
        session_dir=args.session_dir,
        port=args.port,
        labels=json.loads(args.labels),
    )
    manager.start()
    print(f"NODED_READY {manager.address}", flush=True)

    stop = False

    def _term(*_a):
        nonlocal stop
        # SIGTERM is an ANNOUNCED departure (the k8s/GCE preemption
        # path): best-effort drain announce so the head marks the node
        # DRAINING a beat before it vanishes; the announce is async and
        # must not delay the exit below.
        if not stop:
            try:
                manager.begin_drain("SIGTERM", deadline_s=5.0)
            except Exception:  # noqa: BLE001 — exiting regardless
                pass
        stop = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop:
        time.sleep(0.2)
        if args.monitor_pid and not os.path.exists(
                f"/proc/{args.monitor_pid}"):
            logger.warning("monitored pid %d gone; exiting", args.monitor_pid)
            break
    manager.stop()
    sys.exit(0)


if __name__ == "__main__":
    main()
