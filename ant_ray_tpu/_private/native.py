"""Loader for the native extension (native/store_core.cpp).

Builds art_native on demand with the system toolchain into a per-user
cache directory; falls back cleanly (returns None) where no compiler is
available so the pure-Python paths keep working.
"""

from __future__ import annotations

import hashlib
import importlib.util
import logging
import os
import subprocess
import sys
import sysconfig
import threading

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_module = None
_attempted = False


def _source_path() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "native",
        "store_core.cpp")


def _build_dir() -> str:
    src = _source_path()
    digest = hashlib.sha256()
    for path in (src, os.path.join(os.path.dirname(src),
                                   "channel_core.h")):
        try:
            with open(path, "rb") as f:
                digest.update(f.read())
        except FileNotFoundError:
            pass
    d = os.path.join(
        os.path.expanduser("~"), ".cache", "art_native",
        f"{digest.hexdigest()[:16]}"
        f"-py{sys.version_info[0]}{sys.version_info[1]}")
    os.makedirs(d, exist_ok=True)
    return d


def load_native():
    """The art_native module, building it if needed; None if unavailable."""
    global _module, _attempted
    with _lock:
        if _module is not None or _attempted:
            return _module
        _attempted = True
        src = _source_path()
        if not os.path.exists(src):
            return None
        build_dir = _build_dir()
        so_path = os.path.join(build_dir, "art_native.so")
        if not os.path.exists(so_path):
            include = sysconfig.get_path("include")
            # Per-process temp name: concurrent daemon startups may race
            # to build; each compiles privately, rename is atomic.
            tmp_path = f"{so_path}.tmp.{os.getpid()}"
            cmd = [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                f"-I{include}", f"-I{os.path.dirname(src)}",
                src, "-o", tmp_path,
            ]
            try:
                # artlint: disable=blocking-under-lock — serializing
                # the one-time g++ build IS this lock's purpose; every
                # later call returns the cached module without blocking.
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
                os.rename(tmp_path, so_path)
            except (subprocess.CalledProcessError, OSError,
                    subprocess.TimeoutExpired) as e:
                stderr = getattr(e, "stderr", b"")
                logger.warning("art_native build failed: %s %s", e,
                               stderr.decode()[:500] if stderr else "")
                return None
        spec = importlib.util.spec_from_file_location("art_native", so_path)
        module = importlib.util.module_from_spec(spec)
        try:
            spec.loader.exec_module(module)
        except Exception as e:  # noqa: BLE001
            logger.warning("art_native load failed: %s", e)
            return None
        _module = module
        return _module
