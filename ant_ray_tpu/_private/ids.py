"""Binary IDs for every first-class entity in the system.

Design: compact fixed-width binary identifiers, mirroring the semantics of the
reference's id layer (ref: src/ray/common/id.h) without its layout.  IDs embed
lineage where it is useful (an ObjectID embeds the TaskID that creates it, a
TaskID embeds the ActorID/JobID it belongs to) so ownership and lineage walks
never need a table lookup.

Layout (bytes):
    JobID     4   random per driver
    ActorID  12   = JobID(4) + random(8)
    TaskID   20   = ActorID(12) + random(8)       (normal tasks: ActorID = nil-actor of job)
    ObjectID 24   = TaskID(20) + index(4, big-endian)
    NodeID   16   random
    WorkerID 16   random
    PlacementGroupID 16 = JobID(4) + random(12)
"""

from __future__ import annotations

import os
import random
import threading

# Hot-path randomness: ids are minted per task/object on the submission
# path, where os.urandom's syscall (~8µs) dominates.  A process-local
# Mersenne generator seeded from the OS pool keeps ids unique across
# processes (64+ random bits per id) at ~0.5µs a draw.  Workers are
# spawned (never forked), so the state is not duplicated.
_rng = random.Random(os.urandom(16))


def _fast_random_bytes(n: int) -> bytes:
    return _rng.getrandbits(n * 8).to_bytes(n, "big")

_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 12
_TASK_ID_SIZE = 20
_OBJECT_ID_SIZE = 24
_NODE_ID_SIZE = 16
_WORKER_ID_SIZE = 16
_PLACEMENT_GROUP_ID_SIZE = 16


class BaseID:
    """Immutable fixed-width binary id, hashable, hex-printable."""

    SIZE = 0
    __slots__ = ("_bytes", "_hash")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)
        self._hash = hash(self._bytes)

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other) -> bool:
        return self._bytes < other._bytes

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE


class NodeID(BaseID):
    SIZE = _NODE_ID_SIZE


class WorkerID(BaseID):
    SIZE = _WORKER_ID_SIZE


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + _fast_random_bytes(cls.SIZE - JobID.SIZE))

    @classmethod
    def nil_of_job(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + b"\xff" * (cls.SIZE - JobID.SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JobID.SIZE])


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        return cls.for_actor_task(ActorID.nil_of_job(job_id))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary()
                   + _fast_random_bytes(cls.SIZE - ActorID.SIZE))

    @classmethod
    def for_driver_task(cls, job_id: JobID) -> "TaskID":
        return cls.for_normal_task(job_id)

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[:ActorID.SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JobID.SIZE])


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "big"))

    @classmethod
    def from_random(cls) -> "ObjectID":
        # Random put: embed a random "task" so the owner prefix is unique.
        return cls(os.urandom(cls.SIZE))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE:], "big")

    def job_id(self) -> JobID:
        return JobID(self._bytes[:JobID.SIZE])


class PlacementGroupID(BaseID):
    SIZE = _PLACEMENT_GROUP_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(job_id.binary() + os.urandom(cls.SIZE - JobID.SIZE))


class _PutIndexCounter:
    """Monotonic per-task put index so `put` object ids are deterministic
    within a task (ref semantics: ObjectID::FromIndex)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[TaskID, int] = {}

    def next(self, task_id: TaskID) -> int:
        with self._lock:
            n = self._counts.get(task_id, 0) + 1
            self._counts[task_id] = n
            # Put indices live above the return-object index space.
            return 0x8000_0000 + n
