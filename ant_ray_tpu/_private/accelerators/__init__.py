from ant_ray_tpu._private.accelerators import tpu

__all__ = ["tpu"]
