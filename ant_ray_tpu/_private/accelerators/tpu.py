"""TPU accelerator detection and topology metadata.

Parity target: the reference's TPUAcceleratorManager
(ref: python/ray/_private/accelerators/tpu.py:267 — GKE/GCE metadata
detection :105, TPU_VISIBLE_CHIPS :36, valid types v2–v6e :65, topology
tables :88, pod-type inference :151, chips-per-host rule :184).
Redesigned: detection prefers cheap environment/sysfs signals over
importing jax (daemon processes must stay light); the GCE metadata server
is consulted behind a short timeout when env vars are absent (plain GCE
TPU-VMs set no TPU_* env vars — only GKE does); jax is only consulted
when explicitly requested.
"""

from __future__ import annotations

import glob
import logging
import os
import threading
import time
import urllib.error
import urllib.request

from ant_ray_tpu._private.config import global_config

logger = logging.getLogger(__name__)

# Accelerator-type names (resource label values), v2 → v6e.
VALID_TPU_TYPES = (
    "TPU-V2", "TPU-V3", "TPU-V4", "TPU-V5E", "TPU-V5P", "TPU-V6E",
)

# generation → (max chips on a single-host node, peak bf16 TFLOP/s per
# chip, HBM GiB per chip).  v5e/v6e are the 8-chip single-host
# generations (ref: SINGLE_HOST_8_CHIPS_TPU_TYPES, tpu.py:59); all
# others host 4 chips.
TPU_HARDWARE_TABLE: dict[str, tuple[int, float, float]] = {
    "v2": (4, 45.0, 8),
    "v3": (4, 123.0, 16),
    "v4": (4, 275.0, 32),
    "v5e": (8, 197.0, 16),
    "v5p": (4, 459.0, 95),
    "v6e": (8, 918.0, 32),
}

_EIGHT_CHIP_GENERATIONS = ("v5e", "v6e")

# GCE instance-metadata server (ref: GCE_TPU_ACCELERATOR_ENDPOINT,
# tpu.py:27-34).  The host is overridable so tests can stand up a local
# mock; real TPU-VMs resolve metadata.google.internal instantly and
# everything else fails DNS fast.
_METADATA_ATTRIBUTES_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"
)
_METADATA_KEY_ACCELERATOR_TYPE = "accelerator-type"
_METADATA_KEY_INSTANCE_ID = "instance-id"
_METADATA_KEY_WORKER_ID = "agent-worker-number"
_METADATA_KEY_TPU_ENV = "tpu-env"


def _metadata_base_url() -> str:
    return os.environ.get("ART_GCE_METADATA_URL", _METADATA_ATTRIBUTES_URL)


def _sysfs_chip_count() -> int:
    """TPU devices visible in /dev — the cheap "am I a TPU-VM" signal
    that gates metadata-server lookups (CPU hosts must never pay a DNS
    stall in daemon startup)."""
    vfio = glob.glob("/dev/vfio/*")
    accel = glob.glob("/dev/accel*")
    return (len([p for p in vfio if os.path.basename(p) != "vfio"])
            or len(accel))


def _may_query_metadata() -> bool:
    if os.environ.get("ART_GCE_METADATA_URL"):
        return True  # test mock is wired up
    return _sysfs_chip_count() > 0


# Successful lookups (incl. genuine 404 "attribute absent") are cached;
# transient failures are NOT — a metadata server that is briefly slow at
# boot must not pin None for the process lifetime.  After a failure the
# server is considered unreachable for a grace window so the remaining
# keys don't each pay the stall.
_metadata_cache: dict[str, str | None] = {}
_metadata_backoff_until = 0.0
_METADATA_BACKOFF_S = 30.0
_METADATA_DEADLINE_S = 1.0


def _fetch_metadata_once(url: str) -> tuple[bool, str | None]:
    """(ok, value) — run in a worker thread; ok=False means transient."""
    req = urllib.request.Request(url, headers={"Metadata-Flavor": "Google"})
    try:
        with urllib.request.urlopen(
                req, timeout=_METADATA_DEADLINE_S) as resp:
            return True, (resp.read().decode() or None)
    except urllib.error.HTTPError as e:
        if e.code == 404:
            return True, None  # attribute genuinely absent — cacheable
        return False, None
    except (urllib.error.URLError, OSError, ValueError) as e:
        logger.debug("GCE metadata unavailable: %s", e)
        return False, None


def get_tpu_metadata(key: str) -> str | None:
    """One instance-metadata attribute, or None.  The whole lookup —
    including DNS resolution, which urlopen's timeout does not bound —
    runs in a daemon thread joined with a hard deadline, so non-GCE
    hosts (even VFIO-bearing ones with a dead resolver) can't stall
    daemon startup."""
    global _metadata_backoff_until
    if os.environ.get("ART_DISABLE_GCE_METADATA") or \
            not _may_query_metadata():
        return None
    if key in _metadata_cache:
        return _metadata_cache[key]
    if time.monotonic() < _metadata_backoff_until:
        return None
    ok, value = _fetch_metadata_deadline(_metadata_base_url() + key)
    if not ok:
        _metadata_backoff_until = time.monotonic() + _METADATA_BACKOFF_S
        return None
    _metadata_cache[key] = value
    return value


def _fetch_metadata_deadline(url: str) -> tuple[bool, str | None]:
    """_fetch_metadata_once in a daemon thread joined with a hard
    deadline: DNS resolution is NOT bounded by urlopen's timeout, so a
    dead resolver would otherwise hang the caller for minutes — fatal
    for the preemption watcher, whose whole job is reacting within an
    announced grace window."""
    result: list[tuple[bool, str | None]] = []
    t = threading.Thread(
        target=lambda: result.append(_fetch_metadata_once(url)),
        daemon=True)
    t.start()
    t.join(_METADATA_DEADLINE_S + 0.3)
    if not result or not result[0][0]:
        return False, None
    return result[0]


def _metadata_cache_clear() -> None:
    global _metadata_backoff_until
    _metadata_cache.clear()
    _metadata_backoff_until = 0.0


get_tpu_metadata.cache_clear = _metadata_cache_clear  # test hook


def normalize_generation(name: str) -> str:
    """"v5litepod-16" / "TPU-V5E" / "v5e" → "v5e"."""
    name = name.lower().replace("tpu-", "")
    prefix = name.split("-")[0]
    return {"v5litepod": "v5e"}.get(prefix, prefix)


def topology_chip_count(topology: str) -> int:
    """"AxB" / "AxBxC" slice topology → total chips."""
    dims = [int(d) for d in topology.lower().split("x")]
    count = 1
    for d in dims:
        count *= d
    return count


def chips_per_host(topology: str, generation: str) -> int:
    """Chips per VM in a slice (ref rule: get_chips_per_host, tpu.py:184):
    multi-host slices pack 4 chips per VM on every generation; v5e/v6e
    slices of ≤8 chips fit on one VM holding all of them."""
    total = topology_chip_count(topology)
    if total <= 8 and normalize_generation(generation) in \
            _EIGHT_CHIP_GENERATIONS:
        return total
    return 4


def hosts_in_slice(topology: str, generation: str) -> int:
    total = topology_chip_count(topology)
    per_host = chips_per_host(topology, generation)
    return max(1, (total + per_host - 1) // per_host)


def infer_pod_type(topology: str, generation: str) -> str:
    """("4x4", "v5e") → "v5e-16" (ref: infer_tpu_pod_type_from_topology)."""
    return (f"{normalize_generation(generation)}-"
            f"{topology_chip_count(topology)}")


_generation_memo: list = []  # [gen] once positively detected


def detect_generation() -> str | None:
    """TPU generation of this host ("v5e", ...), or None.  Order: explicit
    override → GKE env var → GCE metadata server.  Only POSITIVE results
    memoize — a transiently-unreachable metadata server must not pin
    None for the process lifetime (the metadata layer has its own
    short backoff)."""
    if _generation_memo:
        return _generation_memo[0]
    env = os.environ.get("ART_TPU_GENERATION")
    accel_type = env or os.environ.get("TPU_ACCELERATOR_TYPE")  # GKE
    if not accel_type:
        accel_type = get_tpu_metadata(_METADATA_KEY_ACCELERATOR_TYPE)
    if accel_type:  # e.g. "v5litepod-16"
        gen = normalize_generation(accel_type)
        _generation_memo.append(gen)
        return gen
    return None


def _detect_generation_cache_clear() -> None:
    _generation_memo.clear()


detect_generation.cache_clear = _detect_generation_cache_clear  # test hook


def num_tpu_chips() -> int:
    """Chips attached to this host. Cheap paths first; jax only if the
    platform is already TPU-pinned."""
    override = global_config().tpu_chips_override
    if override >= 0:
        return override
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    count = _sysfs_chip_count()  # vfio/accel devices from the TPU driver
    if count:
        return count
    if os.environ.get("JAX_PLATFORMS", "").lower() in ("tpu", "axon"):
        try:
            import jax  # noqa: PLC0415

            return len([d for d in jax.devices()
                        if d.platform in ("tpu", "axon")])
        except Exception:  # noqa: BLE001
            return 0
    return 0


def current_pod_name() -> str | None:
    """Name of the TPU slice this host belongs to: GKE TPU_NAME env, else
    the GCE instance id (ref: get_current_node_tpu_name, tpu.py:453)."""
    name = os.environ.get("TPU_NAME")
    if name:
        return name
    return get_tpu_metadata(_METADATA_KEY_INSTANCE_ID)


def current_worker_id() -> int:
    """This host's index within its slice: GKE TPU_WORKER_ID env, else the
    GCE agent-worker-number (ref: get_current_node_tpu_worker_id)."""
    wid = os.environ.get("TPU_WORKER_ID")
    if not wid:
        wid = get_tpu_metadata(_METADATA_KEY_WORKER_ID)
    try:
        return int(wid) if wid else 0
    except ValueError:
        return 0


def current_topology() -> str | None:
    topology = os.environ.get("TPU_TOPOLOGY")
    if topology:
        return topology
    # Plain GCE VMs carry the slice env in the `tpu-env` metadata blob
    # (lines of KEY: 'value' pairs, ref: GCE_TPU_ENV_KEY usage).
    blob = get_tpu_metadata(_METADATA_KEY_TPU_ENV)
    if blob:
        for line in blob.splitlines():
            key, _, value = line.partition(":")
            if key.strip() == "TOPOLOGY":
                return value.strip().strip("'\"") or None
    return None


def peak_bf16_tflops(generation: str | None = None) -> float:
    gen = normalize_generation(generation) if generation \
        else (detect_generation() or "v5e")
    return TPU_HARDWARE_TABLE.get(gen, TPU_HARDWARE_TABLE["v5e"])[1]


def hbm_gib_per_chip(generation: str | None = None) -> float:
    gen = normalize_generation(generation) if generation \
        else (detect_generation() or "v5e")
    return TPU_HARDWARE_TABLE.get(gen, TPU_HARDWARE_TABLE["v5e"])[2]


# GCE/TPU maintenance-event surface (ref: the instance metadata
# `maintenance-event` attribute — TPU VMs see "TERMINATE_ON_HOST_
# MAINTENANCE" minutes before an announced preemption; the reference
# consumes the equivalent via the TPU maintenance-event API).
_METADATA_KEY_MAINTENANCE = "maintenance-event"
_MAINTENANCE_NONE = "NONE"


def maintenance_watch_possible() -> bool:
    """Whether ANY notice source could ever fire on this host — the
    daemon's watcher exits immediately when none can (CPU test rigs
    must not pay a poll thread per node forever)."""
    if global_config().testing_preemption_notice:
        return True
    return not os.environ.get("ART_DISABLE_GCE_METADATA") and \
        _may_query_metadata()


def maintenance_notice() -> "tuple[str, float] | None":
    """A pending preemption/maintenance notice for THIS host, or None.

    Returns ``(reason, deadline_s)`` — ``deadline_s`` is the announced
    grace (seconds from now; 0.0 = none announced).  Sources, in order:

    * ``testing_preemption_notice`` (chaos harness): a file path whose
      existence IS the notice; its first line may carry
      ``"<deadline_s> <reason...>"``.
    * The GCE ``maintenance-event`` metadata attribute (un-memoized —
      unlike the identity attributes, this one CHANGES over the
      instance lifetime, so the positive-result cache must not pin it).
    """
    notice_path = global_config().testing_preemption_notice
    if notice_path:
        try:
            with open(notice_path) as f:
                first = f.readline().split(None, 1)
            deadline = float(first[0]) if first else 0.0
            reason = (first[1].strip() if len(first) > 1
                      else "testing preemption notice")
            return reason, deadline
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            return "testing preemption notice", 0.0
    global _metadata_backoff_until
    if os.environ.get("ART_DISABLE_GCE_METADATA") or \
            not _may_query_metadata():
        return None
    if time.monotonic() < _metadata_backoff_until:
        return None
    ok, value = _fetch_metadata_deadline(
        _metadata_base_url() + _METADATA_KEY_MAINTENANCE)
    if not ok:
        # Same backoff as get_tpu_metadata: an unreachable metadata
        # server must not cost the 1 Hz preemption watcher a blocking
        # probe (and a stuck thread) per poll forever.
        _metadata_backoff_until = time.monotonic() + _METADATA_BACKOFF_S
        return None
    if value is None or value.strip() in ("", _MAINTENANCE_NONE):
        return None
    return value.strip(), 0.0


def node_labels() -> dict[str, str]:
    """Labels a node daemon advertises for topology-aware placement
    (ref: TPU-<pod>-head resource + slice labels, util/tpu.py:52)."""
    labels: dict[str, str] = {}
    gen = detect_generation()
    if gen:
        labels["tpu-generation"] = gen
    pod = current_pod_name()
    if pod:
        labels["tpu-pod-name"] = pod
        labels["tpu-worker-id"] = str(current_worker_id())
    topology = current_topology()
    if topology:
        labels["tpu-topology"] = topology
        if gen:
            labels["tpu-pod-type"] = infer_pod_type(topology, gen)
    return labels


def slice_groups(pod_names) -> list:
    """Group ranks by the TPU slice they sit on: ranks whose nodes
    advertise the same ``tpu-pod-name`` label share ICI; distinct pod
    names only reach each other over DCN.  Input is one pod name per
    rank (``None``/"" ranks are treated as a standalone slice each —
    a CPU stand-in host is its own 'slice').  Returns rank tuples,
    ordered by each slice's lowest rank, for
    ``SliceTopology.from_labels``."""
    by_pod: dict = {}
    for rank, pod in enumerate(pod_names):
        key = pod if pod else f"_solo_{rank}"
        by_pod.setdefault(key, []).append(rank)
    return [tuple(ranks)
            for ranks in sorted(by_pod.values(), key=lambda r: r[0])]
