"""TPU accelerator detection and topology metadata.

Parity target: the reference's TPUAcceleratorManager
(ref: python/ray/_private/accelerators/tpu.py:267 — GKE/GCE metadata
detection :105, TPU_VISIBLE_CHIPS :36, valid types v2–v6e :65, topology
tables :88, pod-type inference :151).  Redesigned: detection prefers cheap
environment/sysfs signals over importing jax (daemon processes must stay
light); jax is only consulted when explicitly requested.
"""

from __future__ import annotations

import functools
import glob
import os

from ant_ray_tpu._private.config import global_config

# Accelerator-type names (resource label values), v2 → v6e.
VALID_TPU_TYPES = (
    "TPU-V2", "TPU-V3", "TPU-V4", "TPU-V5E", "TPU-V5P", "TPU-V6E",
)

# generation → (chips per host, peak bf16 TFLOP/s per chip, HBM GiB per chip)
TPU_HARDWARE_TABLE: dict[str, tuple[int, float, float]] = {
    "v2": (4, 45.0, 8),
    "v3": (4, 123.0, 16),
    "v4": (4, 275.0, 32),
    "v5e": (4, 197.0, 16),
    "v5p": (4, 459.0, 95),
    "v6e": (4, 918.0, 32),
}

# pod type → ICI torus topology strings the scheduler understands; a slice
# topology "AxB" or "AxBxC" multiplies to the chip count.
def topology_chip_count(topology: str) -> int:
    dims = [int(d) for d in topology.lower().split("x")]
    count = 1
    for d in dims:
        count *= d
    return count


@functools.lru_cache(maxsize=1)
def detect_generation() -> str | None:
    """TPU generation of this host ("v5e", ...), or None."""
    env = os.environ.get("ART_TPU_GENERATION")
    if env:
        return env
    accel_type = os.environ.get("TPU_ACCELERATOR_TYPE")  # GKE sets this
    if accel_type:  # e.g. "v5litepod-16"
        prefix = accel_type.split("-")[0]
        return {"v5litepod": "v5e", "v5p": "v5p", "v6e": "v6e"}.get(
            prefix, prefix)
    return None


def num_tpu_chips() -> int:
    """Chips attached to this host. Cheap paths first; jax only if the
    platform is already TPU-pinned."""
    override = global_config().tpu_chips_override
    if override >= 0:
        return override
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    # vfio devices exposed by the TPU driver
    vfio = glob.glob("/dev/vfio/*")
    accel = glob.glob("/dev/accel*")
    count = len([p for p in vfio if os.path.basename(p) != "vfio"]) or len(accel)
    if count:
        return count
    if os.environ.get("JAX_PLATFORMS", "").lower() in ("tpu", "axon"):
        try:
            import jax  # noqa: PLC0415

            return len([d for d in jax.devices()
                        if d.platform in ("tpu", "axon")])
        except Exception:  # noqa: BLE001
            return 0
    return 0


def current_pod_name() -> str | None:
    return os.environ.get("TPU_NAME") or None


def current_worker_id() -> int:
    return int(os.environ.get("TPU_WORKER_ID", "0"))


def peak_bf16_tflops(generation: str | None = None) -> float:
    gen = generation or detect_generation() or "v5e"
    return TPU_HARDWARE_TABLE.get(gen, TPU_HARDWARE_TABLE["v5e"])[1]


def hbm_gib_per_chip(generation: str | None = None) -> float:
    gen = generation or detect_generation() or "v5e"
    return TPU_HARDWARE_TABLE.get(gen, TPU_HARDWARE_TABLE["v5e"])[2]


def node_labels() -> dict[str, str]:
    """Labels a node daemon advertises for topology-aware placement
    (ref: TPU-<pod>-head resource + slice labels, util/tpu.py:52)."""
    labels: dict[str, str] = {}
    gen = detect_generation()
    if gen:
        labels["tpu-generation"] = gen
    pod = current_pod_name()
    if pod:
        labels["tpu-pod-name"] = pod
        labels["tpu-worker-id"] = str(current_worker_id())
    topology = os.environ.get("TPU_TOPOLOGY")
    if topology:
        labels["tpu-topology"] = topology
    return labels
