"""Replicated-GCS coordination: election, sync, fencing, HA view.

Role of the reference's GCS-FT blueprint (ref:
src/ray/gcs/store_client/redis_store_client.h + the ant fork's
Redis-lease leader election, python/ray/ha/redis_leader_selector.py):
GCS state externalized to a shared store so a standby head re-hydrates
and takes over — extended here from "restart the head" to a *live*
replica set:

* one **leader** (holds the lease from ``ha/leader_selector.py``)
  applies mutations and write-throughs every table to the store;
* N **warm standbys** tail the same store on a sync loop, serve
  follower reads from their synced tables, and redirect mutations with
  a typed :class:`~ant_ray_tpu._private.protocol.NotLeaderError`;
* failover is lease expiry: a standby acquires, re-hydrates, and starts
  accepting mutations — no process restarts, clients re-resolve through
  ``GetHaView`` (gcs_client.GcsRouter).

Fencing is two-layered: the selector's compare-and-swap lease rejects a
fenced ex-leader's *renewals*, and :meth:`HaCoordinator.mutation_allowed`
additionally checks the lease-validity clock before every mutation, so
an expired-but-not-yet-demoted holder rejects late writes instead of
split-braining.
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import time

from ant_ray_tpu._private import wire_schema
from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.protocol import NotLeaderError

logger = logging.getLogger(__name__)

_HA_TABLE = "ha"


class HaCoordinator:
    """Per-replica HA state machine, composed by ``GcsServer``.

    All io-loop state (role, ads, lag) is owned by the GCS io loop; the
    selector's poll thread only flips GIL-atomic flags and posts the
    promote sequence onto the loop.
    """

    def __init__(self, server, replica_id: str, store_spec: str):
        self._server = server
        self.replica_id = replica_id
        cfg = global_config()
        self._sync_period = cfg.gcs_ha_sync_period_s
        ttl = cfg.gcs_ha_lease_ttl_s
        renew = cfg.gcs_ha_renew_period_s
        if store_spec.startswith("art-store://"):
            from ant_ray_tpu.ha.leader_selector import (  # noqa: PLC0415
                StoreBasedLeaderSelector,
            )

            self._selector = StoreBasedLeaderSelector(
                store_spec, holder_id=replica_id,
                lease_ttl_s=ttl, renew_period_s=renew)
        else:
            from ant_ray_tpu.ha.leader_selector import (  # noqa: PLC0415
                FileBasedLeaderSelector,
            )

            self._selector = FileBasedLeaderSelector(
                store_spec + ".leader-lease", holder_id=replica_id,
                lease_ttl_s=ttl, renew_period_s=renew)
        # True only after the promote sequence (re-hydrate + bookkeeping)
        # completed: the selector may hold the lease while tables are
        # still loading, and mutations must wait for the full state.
        self._active = False
        self.term = 0
        self.last_failover_ts: float | None = None
        self.lag_s: float | None = None      # follower replication lag
        self._leader_ad: dict = {}           # last synced ha/leader row
        self._replica_ads: dict[str, dict] = {}
        # (token, gen) of the leader ad the last table snapshot was
        # taken under — unchanged means the store cannot have moved,
        # so the follower skips the full re-read.
        self._synced_gen: tuple | None = None
        self._sync_task = None

    # ------------------------------------------------------- role / fence

    @property
    def role(self) -> str:
        return "leader" if self.is_leader_active() else "standby"

    def is_leader_active(self) -> bool:
        """Leadership fence: holding the role is not enough — the lease
        must still be inside its validity window, so an ex-leader whose
        lease expired (partition, stalled renew thread) stops acting —
        rejecting late mutations, dropping its self-redirect, reporting
        itself standby — even before the poll thread demotes it."""
        return (self._active and self._selector.is_leader()
                and time.monotonic() < self._selector.lease_valid_until)

    def mutation_allowed(self) -> bool:
        return self.is_leader_active()

    def leader_addr(self) -> str:
        """Best-known leader address for NotLeader redirects ('' when
        no leader is known — e.g. mid-election, or the advertised
        leader stopped refreshing its ad and is presumed dead)."""
        if self.is_leader_active():
            return self._server.address
        ad = self._leader_ad
        addr = ad.get("address", "")
        if addr == self._server.address:
            return ""        # our own stale ad from before a demotion
        # artlint: disable=banned-apis — the ad's ts is a cross-process
        # wire field (leader-written, follower-read); wall clock is the
        # only clock they share.
        if time.time() - float(ad.get("ts") or 0.0) > \
                self._stale_cutoff_s():
            return ""        # dead leader's last ad: don't redirect to it
        return addr

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._selector.on_promote = self._on_promote
        self._selector.on_demote = self._on_demote
        self._sync_task = asyncio.run_coroutine_threadsafe(
            self._sync_loop(), self._server._io.loop)
        self._selector.start()

    def stop(self) -> None:
        if self._sync_task is not None:
            self._sync_task.cancel()
        self._active = False
        # Releases a held lease so standbys take over immediately
        # instead of waiting out the TTL.
        self._selector.stop()

    def wait_until_leader(self, timeout: float | None = None) -> bool:
        if not self._selector.wait_until_leader(timeout):
            return False
        deadline = time.monotonic() + (timeout or 30.0)
        while not self._active and time.monotonic() < deadline:
            time.sleep(0.02)
        return self._active

    # ------------------------------------------------- promotion/demotion

    def _on_promote(self) -> None:       # selector thread
        asyncio.run_coroutine_threadsafe(self._promote(),
                                         self._server._io.loop)

    def _on_demote(self) -> None:        # selector thread
        self._active = False
        logger.warning("GCS replica %s fenced out of leadership",
                       self.replica_id)

    async def _promote(self):
        server = self._server
        if not self._selector.is_leader() or self._active:
            return
        previous = dict(self._leader_ad)
        # Snapshot OFF the io loop: a remote store's reads (and their
        # read fence) block on this very loop, so an inline load would
        # deadlock the whole replica.  Application + activation happen
        # back on the loop in one step, so handlers observe either the
        # pre-promotion synced tables or the complete reload, never a
        # half-applied mix.  A store blip must NOT leave us holding the
        # lease while refusing mutations forever — retry while held.
        while True:
            try:
                snap, term = await asyncio.to_thread(
                    lambda: (server._snapshot_tables_from_store(),
                             self._ha_get_int("term")))
                break
            except Exception:  # noqa: BLE001 — store blip mid-promotion
                logger.exception("promotion re-hydrate failed; retrying")
                await asyncio.sleep(self._sync_period)
                if not self._selector.is_leader():
                    return          # lost the lease while retrying
        if not self._selector.is_leader():
            return                  # fenced while snapshotting
        server._activate_tables(snap)
        self.term = term + 1
        self._ha_put("term", self.term)
        if previous and previous.get("token") != \
                self._selector.fencing_token():
            # A different holder led before us — this promotion IS a
            # failover (first-ever election is not).
            self.last_failover_ts = time.time()
        self.lag_s = None
        self._active = True
        self.write_leader_ad()
        logger.warning(
            "GCS replica %s promoted to leader (term %d%s)",
            self.replica_id, self.term,
            ", failover" if self.last_failover_ts else ", first election")

    # ------------------------------------------------------ store plumbing

    def _ha_put(self, key: str, value) -> None:
        self._server._store.put(_HA_TABLE, key, pickle.dumps(value))

    def _ha_get(self, key: str):
        blob = self._server._store.get(_HA_TABLE, key)
        return pickle.loads(blob) if blob else None

    def _ha_get_int(self, key: str) -> int:
        try:
            return int(self._ha_get(key) or 0)
        except Exception:  # noqa: BLE001 — corrupt counter: restart at 0
            return 0

    def write_leader_ad(self) -> None:
        """Leader heartbeat into the store: address for redirects/
        re-resolve, a fresh wall-clock ts for follower lag measurement,
        and the failover bookkeeping followers mirror into their views.
        Called at promotion and from the leader's flush loop."""
        if not self.is_leader_active():
            return
        self._ha_put("leader", {
            "address": self._server.address,
            "replica_id": self.replica_id,
            "token": self._selector.fencing_token(),
            "term": self.term,
            "last_failover_ts": self.last_failover_ts,
            # Store generation: followers re-read the tables only when
            # this moved (keyed with the token — a new leader's counter
            # restarts, so the pair changes across failovers).
            "gen": self._server._store_gen,
            "ts": time.time(),
        })

    def _stale_cutoff_s(self) -> float:
        cfg = global_config()
        return max(5 * cfg.gcs_ha_sync_period_s,
                   2 * cfg.gcs_ha_lease_ttl_s)

    # ------------------------------------------------------------ syncing

    async def _sync_loop(self):
        """Every replica: advertise itself and refresh the peer view;
        standbys additionally re-hydrate their tables from the store
        (the warm part of "warm standby")."""
        while True:
            try:
                await self._sync_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — store blip: retry next tick
                logger.exception("HA sync iteration failed")
            await asyncio.sleep(self._sync_period)

    async def _sync_once(self):
        server = self._server
        follower = not self.is_leader_active()

        def _store_side():
            self._ha_put("replica:" + self.replica_id, {
                "replica_id": self.replica_id,
                "address": server.address,
                "role": self.role,
                "lag_s": self.lag_s,
                "ts": time.time(),
            })
            ads = {}
            for key, blob in server._store.load_table(_HA_TABLE).items():
                if not key.startswith("replica:"):
                    continue
                try:
                    ads[key[len("replica:"):]] = pickle.loads(blob)
                except Exception:  # noqa: BLE001 — torn ad: skip
                    pass
            leader_ad = self._ha_get("leader") or {}
            tables = None
            if follower:
                # Re-read the tables only when the leader's store
                # generation moved (or the ad predates generations) —
                # an idle cluster's sync is then O(ads), not O(state).
                gen_key = (leader_ad.get("token"), leader_ad.get("gen"))
                if leader_ad.get("gen") is None or \
                        gen_key != self._synced_gen:
                    tables = server._snapshot_tables_from_store()
            return ads, leader_ad, tables

        ads, leader_ad, tables = await asyncio.to_thread(_store_side)
        self._replica_ads = ads
        if self.is_leader_active():
            return                      # promoted mid-snapshot: discard
        self._leader_ad = leader_ad
        if tables is not None:
            server._apply_table_snapshot(tables)
            self._synced_gen = (leader_ad.get("token"),
                                leader_ad.get("gen"))
        ad_ts = leader_ad.get("ts")
        if ad_ts:
            # artlint: disable=banned-apis — the leader ad's ts is a
            # CROSS-PROCESS wire field (written by the leader, read by
            # every follower); wall clock is the only clock they share.
            self.lag_s = max(0.0, time.time() - ad_ts)
        self.term = int(leader_ad.get("term", self.term) or 0)
        if leader_ad.get("last_failover_ts"):
            self.last_failover_ts = leader_ad["last_failover_ts"]

    # ------------------------------------------------------------ surface

    def view(self) -> dict:
        now = time.time()
        cutoff = self._stale_cutoff_s()
        replicas = []
        for ad in self._replica_ads.values():
            # artlint: disable=banned-apis — replica-ad ts is a cross-
            # process wire field (see the sync-loop note above).
            age = max(0.0, now - float(ad.get("ts") or 0.0))
            if age > cutoff:
                continue                 # dead replica's last ad
            replicas.append({
                "replica_id": ad.get("replica_id"),
                "address": ad.get("address"),
                "role": ad.get("role"),
                "lag_s": ad.get("lag_s"),
                "age_s": age,
            })
        replicas.sort(key=lambda r: (r["role"] != "leader",
                                     str(r["replica_id"])))
        return {
            "ha": True,
            "role": self.role,
            "replica_id": self.replica_id,
            "address": self._server.address,
            "leader": self.leader_addr(),
            "term": self.term,
            "last_failover_ts": self.last_failover_ts,
            "replication_lag_s": self.lag_s,
            "replicas": replicas,
        }

    def peer_addresses(self) -> list[str]:
        """Live peer replica addresses (self excluded) — the ring-merge
        fan-out set."""
        now = time.time()
        cutoff = self._stale_cutoff_s()
        out = []
        for ad in self._replica_ads.values():
            addr = ad.get("address")
            # artlint: disable=banned-apis — replica-ad ts: cross-
            # process wire field (see the sync-loop note above).
            if addr and addr != self._server.address and \
                    now - float(ad.get("ts") or 0.0) <= cutoff:
                out.append(addr)
        return out

    async def gather_ring(self, method: str, payload: dict) -> list:
        """Query-time merge fan-out: ask every live peer replica for its
        LOCAL slice of a sharded ring (``local_only=True`` stops the
        recursion) and return the successful replies.  A dead peer's
        slice is simply absent — the rings are bounded best-effort
        buffers; durability of the critical records (terminal task
        states) comes from producer-side replay, not from here."""
        peers = self.peer_addresses()
        if not peers:
            return []

        async def one(addr):
            try:
                return await self._server._clients.get(addr).call_async(
                    method, {**(payload or {}), "local_only": True},
                    timeout=5)
            except Exception:  # noqa: BLE001 — peer down/restarting
                return None

        replies = await asyncio.gather(*[one(a) for a in peers])
        return [r for r in replies if r is not None]

    # ------------------------------------------------------------- guard

    def guard_routes(self, handlers: dict) -> dict:
        """Wrap every leader-only method with the mutation fence; reads
        and ring writes pass through (served by any replica).  The
        split comes from wire_schema so server and client router can
        never disagree."""
        mutations = wire_schema.gcs_mutations()
        out = {}
        for method, handler in handlers.items():
            if method in mutations:
                out[method] = self._guarded(handler)
            else:
                out[method] = handler
        return out

    def _guarded(self, handler):
        async def guarded(payload):
            if not self.mutation_allowed():
                raise NotLeaderError(self.leader_addr())
            return await handler(payload)

        return guarded
