"""Implicit init on first API use (ref: ray auto-init semantics)."""

from __future__ import annotations

import os


def auto_init() -> None:
    from ant_ray_tpu import api  # noqa: PLC0415
    from ant_ray_tpu._private.worker import global_worker  # noqa: PLC0415

    if global_worker.connected:
        return
    address = os.environ.get("ART_ADDRESS")
    api.init(address=address, ignore_reinit_error=True)
