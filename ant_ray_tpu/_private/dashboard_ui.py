"""The dashboard web UI: one self-contained HTML page (no build step,
no bundled JS framework) served at ``/``, polling the REST API the
dashboard already exposes (ref capability: python/ray/dashboard/ —
the reference ships a React SPA; this stack serves an equivalent
operator view as a static page, so the UI works wherever the head
runs with zero frontend toolchain).
"""

INDEX_HTML = """<!doctype html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>ant-ray-tpu dashboard</title>
<style>
  :root { --fg:#1a1a2e; --muted:#667; --line:#e3e3ee; --accent:#34508c;
          --bg:#fafafc; --card:#fff; }
  body { margin:0; font:14px/1.45 system-ui,sans-serif; color:var(--fg);
         background:var(--bg); }
  header { padding:10px 20px; background:var(--card);
           border-bottom:1px solid var(--line); display:flex;
           align-items:baseline; gap:16px; }
  header h1 { font-size:16px; margin:0; }
  header span { color:var(--muted); font-size:12px; }
  nav { display:flex; gap:2px; padding:0 20px; background:var(--card);
        border-bottom:1px solid var(--line); }
  nav button { border:0; background:none; padding:9px 14px; font:inherit;
               cursor:pointer; color:var(--muted);
               border-bottom:2px solid transparent; }
  nav button.active { color:var(--accent);
                      border-bottom-color:var(--accent); }
  main { padding:16px 20px; max-width:1100px; }
  table { border-collapse:collapse; width:100%; background:var(--card);
          border:1px solid var(--line); margin-bottom:18px; }
  th, td { text-align:left; padding:6px 10px;
           border-bottom:1px solid var(--line); vertical-align:top; }
  th { font-weight:600; font-size:12px; color:var(--muted);
       text-transform:uppercase; letter-spacing:.03em; }
  tr:last-child td { border-bottom:0; }
  code, pre { font:12px/1.4 ui-monospace,monospace; }
  pre { background:var(--card); border:1px solid var(--line);
        padding:10px; overflow:auto; max-height:480px; }
  .dead { color:#a33; } .alive { color:#286b3c; }
  h2 { font-size:14px; margin:18px 0 8px; }
  form { margin-bottom:14px; display:flex; gap:8px; }
  input[type=text] { flex:1; padding:6px 8px; font:inherit;
                     border:1px solid var(--line); border-radius:3px; }
  button.act { padding:6px 12px; font:inherit; cursor:pointer;
               border:1px solid var(--accent); background:var(--accent);
               color:#fff; border-radius:3px; }
  a { color:var(--accent); }
  .err { color:#a33; white-space:pre-wrap; }
</style>
</head>
<body>
<header><h1>ant-ray-tpu</h1><span id="meta">connecting…</span></header>
<nav id="tabs"></nav>
<main id="view">loading…</main>
<script>
"use strict";
const TABS = ["overview","nodes","actors","placement groups","jobs",
              "logs"];
let tab = "overview", timer = null, logFile = null;

const $ = (h) => { const d = document.createElement("div");
                   d.innerHTML = h; return d; };
const esc = (s) => String(s ?? "").replace(/[&<>"']/g,
    c => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;",
           "'":"&#39;"}[c]));
const get = async (p) => { const r = await fetch(p);
                           if (!r.ok) throw new Error(p+": "+r.status);
                           return r.json(); };
const fmtRes = (o) => Object.entries(o || {})
    .map(([k, v]) => k+": "+(+(+v).toFixed(2))).join(", ");
const table = (heads, rows) =>
    "<table><tr>" + heads.map(h => "<th>"+h+"</th>").join("") + "</tr>" +
    (rows.length ? rows.map(r => "<tr>" + r.map(c => "<td>"+c+"</td>")
     .join("") + "</tr>").join("")
     : "<tr><td colspan="+heads.length+">none</td></tr>") + "</table>";

async function renderOverview() {
  const [s, actors, pgs, jobs] = await Promise.all([
      get("/api/cluster_status"), get("/api/actors"),
      get("/api/placement_groups"), get("/api/jobs")]);
  const avail = s.resources_available || {},
        tot = s.resources_total || {};
  const rows = Object.keys(tot).sort().map(k =>
      [esc(k), +(+ (avail[k] ?? 0)).toFixed(2), +(+tot[k]).toFixed(2)]);
  return "<h2>Cluster</h2>" +
    table(["", ""], [["Alive nodes", s.nodes_alive ?? "?"],
                     ["Dead nodes", s.nodes_dead ?? 0],
                     ["Actors", actors.length],
                     ["Placement groups", Object.keys(pgs).length],
                     ["Jobs", jobs.length]]) +
    "<h2>Resources</h2>" +
    table(["Resource", "Available", "Total"], rows) +
    "<p><a href='/metrics'>Prometheus metrics</a> · " +
    "<a href='/api/timeline'>Chrome timeline (JSON)</a> · " +
    "<a href='/api/insight'>Flow insight</a></p>";
}

async function renderNodes() {
  const nodes = await get("/api/nodes");
  return table(
    ["Node", "State", "Address", "Available", "Total", "Labels"],
    nodes.map(n => [
      "<code>"+esc((n.node_id||"").slice(0,12))+"</code>",
      n.alive ? "<span class=alive>ALIVE</span>"
              : "<span class=dead>DEAD</span>",
      esc(n.address || ""),
      esc(fmtRes(n.available_resources)),
      esc(fmtRes(n.total_resources)),
      esc(Object.entries(n.labels || {})
          .map(([k,v]) => k+"="+v).join(", "))]));
}

async function renderActors() {
  const actors = await get("/api/actors");
  return table(["Actor", "Class", "State", "Name", "Death reason"],
    actors.map(a => [
      "<code>"+esc((a.actor_id||"").slice(0,12))+"</code>",
      esc(a.class_name || ""),
      a.state === "ALIVE" ? "<span class=alive>ALIVE</span>"
                          : esc(a.state || ""),
      esc(a.name || ""), esc(a.death_reason || "")]));
}

async function renderPgs() {
  const pgs = await get("/api/placement_groups");
  return table(["PG", "Name", "Strategy", "State", "Bundles"],
    Object.entries(pgs).map(([id, p]) => [
      "<code>"+esc(id.slice(0,12))+"</code>",
      esc(p.name||""), esc(p.strategy||""), esc(p.state||""),
      esc((p.bundles||[]).map(b => fmtRes(b)).join(" | "))]));
}

async function renderJobs() {
  const jobs = await get("/api/jobs");
  const rows = jobs.map(j => [
      "<code>"+esc(j.submission_id||"")+"</code>",
      esc(j.entrypoint||""), esc(j.status||""),
      "<a href='#' class=joblink data-job=\\""+esc(j.submission_id)+
      "\\">logs</a>"]);
  return "<form onsubmit='submitJob(event)'>" +
    "<input type=text id=entry placeholder='entrypoint, e.g. python my_script.py'>" +
    "<button class=act>Submit job</button></form>" +
    table(["Job", "Entrypoint", "Status", ""], rows) +
    "<div id=joblog>" + jobLogHtml + "</div>";
}

window.submitJob = async (ev) => {
  ev.preventDefault();
  const entrypoint = document.getElementById("entry").value.trim();
  if (!entrypoint) return;
  await fetch("/api/jobs", {method:"POST",
      headers:{"content-type":"application/json"},
      body: JSON.stringify({entrypoint})});
  render();
};
let jobLogHtml = "";
window.jobLogs = async (id) => {
  const out = await get("/api/jobs/"+id+"/logs");
  jobLogHtml = "<h2>logs: "+esc(id)+"</h2><pre>"+esc(out.logs)+
               "</pre>";
  const el = document.getElementById("joblog");
  if (el) el.innerHTML = jobLogHtml;
};
document.addEventListener("click", (ev) => {
  const a = ev.target.closest("a.joblink, a.loglink");
  if (!a) return;
  ev.preventDefault();
  if (a.classList.contains("joblink")) jobLogs(a.dataset.job);
  else openLog(a.dataset.file, a.dataset.node);
});

async function renderLogs() {
  const nodes = await get("/api/logs");
  let html = "";
  for (const n of nodes) {
    html += "<h2>node <code>"+esc(n.node_id.slice(0,12))+"</code></h2>" +
      table(["File", "Bytes"], (n.files||[]).map(f => [
        "<a href='#' class=loglink data-file=\\""+esc(f.filename)+
        "\\" data-node=\\""+esc(n.node_id)+"\\">"+
        esc(f.filename)+"</a>",
        esc(f.size ?? "")]));
  }
  if (logFile) {
    const body = await get("/api/logs/" + encodeURIComponent(logFile) +
        "?tail=200&node_id=" + encodeURIComponent(logNode || ""));
    html += "<h2>"+esc(logFile)+"</h2><pre>" +
            esc(body.error || body.data) + "</pre>";
  }
  return html;
}
let logNode = null;\nwindow.openLog = (f, n) => { logFile = f; logNode = n; render(); };

const RENDER = {"overview": renderOverview, "nodes": renderNodes,
                "actors": renderActors, "placement groups": renderPgs,
                "jobs": renderJobs, "logs": renderLogs};

async function render(auto) {
  const entry = document.getElementById("entry");
  if (auto && entry && (document.activeElement === entry ||
                        entry.value)) {
    return;    // don't wipe in-progress input on the refresh tick
  }
  const view = document.getElementById("view");
  try {
    view.innerHTML = await RENDER[tab]();
    document.getElementById("meta").textContent =
        new Date().toLocaleTimeString();
  } catch (e) {
    view.innerHTML = "<p class=err>"+esc(e)+"</p>";
  }
}

function setTab(t) {
  tab = t; logFile = null;
  document.querySelectorAll("nav button").forEach(b =>
      b.classList.toggle("active", b.textContent === t));
  render();
}

const nav = document.getElementById("tabs");
TABS.forEach(t => {
  const b = document.createElement("button");
  b.textContent = t;
  b.onclick = () => setTab(t);
  nav.appendChild(b);
});
setTab("overview");
timer = setInterval(() => render(true), 4000);
</script>
</body>
</html>
"""
