"""Per-task / per-actor submission options (ref: @ray.remote(**opts) surface,
python/ray/_private/ray_option_utils.py)."""

from __future__ import annotations

import dataclasses
from typing import Any


@dataclasses.dataclass
class TaskOptions:
    num_cpus: float | None = None
    num_tpus: float | None = None          # TPU chips (ref uses resources={"TPU": n})
    num_gpus: float | None = None          # accepted for API parity; maps to resources
    resources: dict[str, float] = dataclasses.field(default_factory=dict)
    num_returns: int = 1
    max_retries: int | None = None
    retry_exceptions: bool = False
    name: str = ""
    runtime_env: dict | None = None
    scheduling_strategy: Any = None        # "DEFAULT" | "SPREAD" | PG strategy
    placement_group: Any = None
    placement_group_bundle_index: int = -1
    # Node-label constraint, e.g. {"tpu-pod-name": "slice-A"}
    # (ref: @ray.remote(label_selector=...))
    label_selector: dict | None = None
    # Actor-method routing to a named executor pool (actor tasks only;
    # ref: @ray.method(concurrency_group=...))
    concurrency_group: str = ""
    _metadata: dict = dataclasses.field(default_factory=dict)

    def resource_demand(self, default_num_cpus: float = 1.0) -> dict[str, float]:
        demand = dict(self.resources)
        cpus = self.num_cpus if self.num_cpus is not None else default_num_cpus
        if cpus:
            demand["CPU"] = demand.get("CPU", 0.0) + cpus
        if self.num_tpus:
            demand["TPU"] = demand.get("TPU", 0.0) + self.num_tpus
        if self.num_gpus:
            demand["GPU"] = demand.get("GPU", 0.0) + self.num_gpus
        return demand

    def merged_with(self, **overrides) -> "TaskOptions":
        new = dataclasses.replace(self)
        for key, value in overrides.items():
            if value is None and key != "scheduling_strategy":
                continue
            if not hasattr(new, key):
                raise ValueError(f"Unknown option {key!r}")
            setattr(new, key, value)
        return new


@dataclasses.dataclass
class ActorOptions(TaskOptions):
    max_restarts: int | None = None
    max_task_retries: int = 0
    max_concurrency: int = 1
    # Named bounded thread pools, e.g. {"io": 2, "compute": 4} (ref:
    # @ray.remote(concurrency_groups=...), concurrency_group_manager.h)
    concurrency_groups: dict[str, int] | None = None
    max_pending_calls: int = -1
    lifetime: str | None = None            # None | "detached"
    namespace: str | None = None
    get_if_exists: bool = False

    def resource_demand(self, default_num_cpus: float = 0.0) -> dict[str, float]:
        """Resources held while the actor is alive.  Default 0 CPU (ref
        semantics: running actors hold no CPU), so long-lived actors don't
        starve task scheduling; explicit num_cpus/num_tpus are held."""
        return super().resource_demand(default_num_cpus)

    def placement_demand(self) -> dict[str, float]:
        """Resources the scheduler matches when *placing* the actor —
        default 1 CPU (ref semantics: placement uses 1 CPU, running uses
        0), which bounds how many default actors pack onto a node."""
        return super().resource_demand(1.0)
