"""Wire-schema registry: the versioned contract behind every RPC method
(ref: the reference's protobuf schemas, src/ray/protobuf/*.proto — here
the frames are pickled tuples ``(kind, seq, method, payload)``, so the
schema lives in this registry instead of .proto files, and the
connection-level version fence lives in protocol.PROTOCOL_VERSION).

Every method a server registers MUST have an entry here (enforced by
tests/test_wire_schema.py, which parses the registration blocks of the
service sources).  An entry records:

* ``service`` — which server exposes it,
* ``since``  — the protocol version that introduced it,
* ``payload`` / ``reply`` — one-line field contract.

Evolution rules (the versioning policy):

1. ADDING a method or an OPTIONAL payload key (readers use .get) is
   allowed within a protocol version — add the entry with the current
   ``since``.
2. REMOVING or RENAMING a method/field, or changing a field's meaning,
   requires bumping protocol.PROTOCOL_VERSION — mixed-version peers
   then fail fast at connect instead of mis-decoding frames.
3. Frame-shape changes (the tuple itself) always bump the version.
"""

from __future__ import annotations

V1 = 1


def _m(service: str, payload: str, reply: str, since: int = V1) -> dict:
    return {"service": service, "since": since,
            "payload": payload, "reply": reply}


METHODS: dict[str, dict] = {
    # ---- GCS (cluster head) -------------------------------------------
    "RegisterNode": _m("gcs", "NodeInfo", "bool"),
    "Heartbeat": _m("gcs", "{node_id, view_version?, view?}",
                    "{resync?, commands?}"),
    "GetAllNodes": _m("gcs", "{}", "{node_id: NodeInfo}"),
    "ListNodes": _m("gcs", "{limit?, token?, state?}",
                    "{nodes: [dict], next_token?, total, matched} "
                    "(server-side page + state filter; the ListTasks "
                    "cursor idiom over the node table)"),
    "GetScaleStats": _m("gcs", "{}",
                        "{table_rows, rings, subscribers, sched, "
                        "heartbeat, handle, io_loop_duty} (the scale "
                        "observatory's per-subsystem cost counters — "
                        "LOCAL introspection: any replica serves its "
                        "own process's view, so per-replica cost is "
                        "separable under HA)"),
    "DrainNode": _m("gcs", "{node_id, reason?, deadline?}",
                    "bool (node enters DRAINING: schedulers skip it, "
                    "Serve/Train migrate off it)"),
    "KVPut": _m("gcs", "{key, value, overwrite?}", "bool"),
    "KVGet": _m("gcs", "{key, fence?}",
                "bytes|None (fence: a follower answers through the "
                "shared store — read-your-writes across the HA split)"),
    "KVDel": _m("gcs", "{key}", "bool"),
    "KVTake": _m("gcs", "{key}", "bytes|None (atomic get+del)"),
    "KVKeys": _m("gcs", "{prefix}", "[key]"),
    "RegisterJob": _m("gcs", "{job_id, driver_address}", "bool"),
    "CreateActor": _m("gcs", "ActorSpec", "{actor_id|error}"),
    "GetActorInfo": _m("gcs", "{actor_id}", "{state, address, ...}"),
    "WaitActorAlive": _m("gcs", "{actor_id, timeout}",
                         "{state, address}"),
    "GetNamedActor": _m("gcs", "{name, namespace}", "{actor_id|None}"),
    "KillActor": _m("gcs", "{actor_id, no_restart}", "bool"),
    "ActorStateUpdate": _m("gcs", "{actor_id, state, address?, reason?}",
                           "bool"),
    "WorkerDied": _m("gcs", "{node_id, worker_id, actor_id?, reason}",
                     "bool"),
    "ObjectLocationAdd": _m("gcs", "{object_id, node_id, owner?, "
                                   "callsite?}", "bool"),
    "ObjectLocationRemove": _m("gcs", "{object_id, node_id}", "bool"),
    "ObjectLocationsGet": _m("gcs", "{object_id}", "[NodeInfo]"),
    "FreeObject": _m("gcs", "{object_id}", "bool (cluster-wide free)"),
    "SelectNode": _m("gcs",
                     "{resources, job_id?, label_selector?, strategy?, "
                     "exclude?}", "NodeInfo|None"),
    "ResourceDemands": _m("gcs", "{demands: [...]} (from daemons)",
                          "[{resources|bundles, count, ...}]"),
    "AutoscalerHeartbeat": _m("gcs", "{}", "bool"),
    "AutoscalingEnabled": _m("gcs", "{}", "bool"),
    "ClusterResources": _m("gcs", "{}", "{resource: total}"),
    "AvailableResources": _m("gcs", "{}", "{resource: available}"),
    "CreatePlacementGroup": _m(
        "gcs", "{pg_id, bundles, strategy, name?, job_id?, "
               "bundle_label_selectors?, same_label?}", "bool"),
    "GetPlacementGroup": _m("gcs", "{pg_id}", "record dict"),
    "RemovePlacementGroup": _m("gcs", "{pg_id}", "bool"),
    "ListPlacementGroups": _m("gcs", "{}", "[record]"),
    "ListActors": _m("gcs", "{}", "[{actor_id, state, ...}]"),
    "ListObjects": _m("gcs", "{}", "[{object_id, locations}]"),
    "MetricRecord": _m("gcs", "{name, tags, value, kind}", "bool"),
    "MetricsGet": _m("gcs", "{}", "[series]"),
    "CreateVirtualCluster": _m("gcs", "{vc_id, node_ids, divisible}",
                               "bool"),
    "RemoveVirtualCluster": _m("gcs", "{vc_id}", "bool"),
    "UpdateVirtualCluster": _m("gcs", "{vc_id, node_ids}", "bool"),
    "ListVirtualClusters": _m("gcs", "{}", "[vc record]"),
    "SetJobVirtualCluster": _m("gcs", "{job_id, vc_id|None}", "bool"),
    "GetJobVirtualCluster": _m("gcs", "{job_id}",
                               "{allowed: [node_id]|None}"),
    "InsightRecord": _m("gcs", "{events: [...]}", "bool"),
    "InsightGet": _m("gcs", "{limit?}", "[event]"),
    "TaskEventsAdd": _m("gcs", "{events: [{task_id, name, event, ...}], "
                               "dropped?}", "bool"),
    "TaskEventsGet": _m("gcs", "{limit?, task_id?, local_only?}",
                        "[event] (local_only: this replica's ring "
                        "slice only — the HA merge fan-out)"),
    "ListTasks": _m("gcs",
                    "{state?, name?, job_id?, actor_id?, node_id?, "
                    "limit?, token?, local_only?}",
                    "{tasks: [record], next_token?, num_tasks_dropped, "
                    "task_events_dropped} — served from the bounded "
                    "GCS state table with server-side filtering; the "
                    "client never pulls the raw event ring"),
    "GetTask": _m("gcs", "{task_id, local_only?}",
                  "{task_id, attempts: [record], stats}|None"),
    "SummarizeTasks": _m("gcs", "{job_id?, node_id?, local_only?}",
                         "{summary: {name: {state_counts, run_s: "
                         "{mean, p50, p99}}}, total_tasks, "
                         "num_tasks_dropped, task_events_dropped}"),
    "ListJobs": _m("gcs", "{}",
                   "[{job_id, driver_address, started_at}]"),
    "StepEventsAdd": _m("gcs", "{records: [{step, ts, total_s, phases, "
                               "mfu?, rank}]}", "bool"),
    "StepEventsGet": _m("gcs", "{limit?, rank?, local_only?}",
                        "[record]"),
    "SpanEventsAdd": _m("gcs", "{spans: [{trace_id, span_id, parent_id, "
                               "name, ts, dur_s, stages?, attrs?, "
                               "error?, node_id, pid}]}", "bool"),
    "SpanEventsGet": _m("gcs", "{limit?, trace_id?, node_id?, "
                               "errors_only?, local_only?}", "[span]"),
    "CpuProfileAdd": _m("gcs", "{records: [{node_id, pid, proc, ts, "
                               "dur_s, hz, samples, stacks: "
                               "{folded: count}}]}", "bool"),
    "CpuProfileGet": _m("gcs", "{limit?, node_id?, proc?, since_ts?, "
                               "local_only?}", "[record]"),
    "MetricsExpire": _m("gcs", "{match_tags?, name_prefix?}",
                        "int (series dropped; per-entity gauge owners "
                        "call this at teardown so dead nodes/replicas "
                        "don't live in /metrics forever)"),
    "GetHaView": _m("gcs", "{}",
                    "{ha, role, replica_id, address, leader, term, "
                    "last_failover_ts, replication_lag_s, replicas: "
                    "[{replica_id, address, role, lag_s, age_s}]} — "
                    "served by ANY replica (leader or standby); the "
                    "client router re-resolves the leader through it "
                    "after a failover"),
    "SubPoll": _m("gcs", "{channels, cursor, timeout}",
                  "{cursor, events: [(seq, channel, data)]}"),
    "PublishLogs": _m("gcs", "{node, entries: [{worker, pid, job_id?, "
                             "lines}]}", "bool"),
    "ExportEventsGet": _m("gcs", "{source_type?, limit?}",
                          "{enabled, events}"),
    "Shutdown": _m("gcs|node", "{}", "bool"),

    # ---- node daemon (raylet) -----------------------------------------
    "LeaseWorker": _m("node",
                      "{resources, job_id?, label_selector?, strategy?, "
                      "pg?, runtime_env?, deps?, routed?, count?}",
                      "{granted, worker_id, extra?: [{granted, "
                      "worker_id}]}|{spill}|{infeasible, reason} — "
                      "count asks for a batch of leases in one round "
                      "trip; extras come only from already-idle "
                      "capacity (both keys additive: old peers ignore "
                      "count / never send it)"),
    "ReturnWorker": _m("node", "{worker_id}", "bool"),
    "RegisterWorker": _m("node", "{worker_id, address, pid}",
                         "{ok}|{error}"),
    "StartActorWorker": _m("node", "{spec, pg?}", "{ok}|{infeasible}"),
    "KillActorWorker": _m("node", "{worker_id|actor_id}", "bool"),
    "WorkerBlocked": _m("node", "{worker_id}", "bool (cpu released)"),
    "WorkerUnblocked": _m("node", "{worker_id}", "bool"),
    "PrepareBundle": _m("node", "{pg_id, bundle_index, resources}",
                        "bool (2-phase commit phase 1)"),
    "CommitBundle": _m("node", "{pg_id, bundle_index}", "bool"),
    "ReturnBundle": _m("node", "{pg_id, bundle_index}", "bool"),
    "CreateBuffer": _m("node", "{object_id, size}",
                       "{path, offset} write grant"),
    "SealBuffer": _m("node", "{object_id}", "bool"),
    "SealObject": _m("node", "{object_id, data}", "bool"),
    "DeleteObject": _m("node", "{object_id}", "bool"),
    "ContainsObject": _m("node", "{object_id}", "bool"),
    "LocateObject": _m("node", "{object_id}",
                       "{size, ...}|None (transfer source probe)"),
    "ReadChunk": _m("node", "{object_id, offset, length}", "bytes"),
    "ReadChunkRaw": _m("node", "{object_id, offset, length, stripe?}",
                       "raw out-of-band frame: chunk bytes served "
                       "zero-copy (b'' past EOF, None when missing)"),
    "EnsureLocal": _m("node",
                      "{object_id, timeout, fail_fast_after?, pin_ttl?, "
                      "prefetch?}",
                      "{path, offset, size, pinned?, pin_token?}|"
                      "{no_holders}|{timeout}|{ok}"),
    "ReadDone": _m("node", "{object_id, pin_token}", "bool"),
    "RenewPins": _m("node", "{pins: [(oid, token)], ttl}", "{gone: []}"),
    "GetNodeInfo": _m("node", "{}", "NodeInfo"),
    "NotifyDrain": _m("node", "{reason?, deadline_s?}",
                      "bool (daemon self-drains + announces via "
                      "DrainNode; the operator/chaos drain surface)"),
    "DebugResources": _m("node", "{}",
                         "{available, bundles, workers} ledger dump"),
    "GetNodeMetrics": _m("node", "{}", "{gauges}"),
    "GetFlightRecorder": _m("node", "{limit?}",
                            "{node_id, spans} — this daemon process's "
                            "live flight-recorder ring (always on; "
                            "force-sampled error spans in their own "
                            "wrap-protected ring)"),
    "GetStoreStats": _m("node", "{}", "{used, capacity, spilled}"),
    "ListObjectStats": _m("node", "{}",
                          "{node_id, objects: [{object_id, size, "
                          "pins, sealed, tier, created_age_s, "
                          "chunk_cache_bytes}], store: {used, "
                          "capacity, spilled}} — per-object arena "
                          "detail behind `art memory` / /api/memory"),
    "GetSyncStats": _m("node", "{}", "{beats, views_sent, ...}"),
    "GetTransferStats": _m("node", "{include_read_log?}",
                           "{quota_waits, ..., read_log?}"),
    "ListLogs": _m("node", "{}", "[{filename, size}]"),
    "ReadLog": _m("node", "{filename, offset?, tail?, max_bytes?}",
                  "{data, next_offset, eof}|{error}"),

    # ---- worker / owner (core runtime) --------------------------------
    "PushTask": _m("worker", "TaskSpec (fast route)",
                   "result payload — between hot-wire peers this "
                   "method rides HOT frames (hotframe.py: templated "
                   "zero-pickle calls, coalesced batched acks); the "
                   "pickled form stays the negotiation fallback"),
    "CancelTask": _m("worker", "{task_id}",
                     "bool — drop the task if it has not started "
                     "executing (oneway from owners; cooperative: "
                     "running tasks are never interrupted)"),
    "InstantiateActor": _m("worker", "ActorSpec", "bool"),
    "Ping": _m("worker|store", "{}", "'pong'"),
    "GetObject": _m("worker", "{object_id, timeout}",
                    "(kind, payload) owned-object fetch"),
    "GetObjectStatus": _m("worker", "{object_id}",
                          "'ready'|'pending'|'unknown'"),
    "GetObjectStatusBatch": _m("worker", "{object_ids: [oid]}",
                               "{oid: 'ready'|'pending'|'unknown'}"),
    "WaitObjects": _m("worker",
                      "{object_ids: [oid], num_ready?, timeout?}",
                      "{oid: status} — owner parks the reply until "
                      "num_ready listed refs are terminal or the "
                      "deadline fires (push-based wait)"),
    "GetObjectInfo": _m("worker", "{object_id}", "{status, size}"),
    "GetOwnedRefInfo": _m("worker", "{object_ids: [hex]}",
                          "{hex: {local_refs, borrows, pins}|None} — "
                          "owner-side refcounts for the memory-"
                          "attribution leak scan (None = the owner "
                          "holds no reference state for the id)"),
    "BorrowAdd": _m("worker", "{object_id}", "bool"),
    "BorrowRemove": _m("worker", "{object_id}", "bool"),
    "ReconstructObject": _m("worker", "{object_id}",
                            "bool (lineage re-execution)"),
    "StreamItem": _m("worker", "{task_id, index, payload|done}", "bool"),
    "DeviceTensorFetch": _m("worker", "{token}", "host tensor bytes"),
    "DeviceTensorFree": _m("worker", "{token}", "bool"),
    "DeviceTensorSendVia": _m("worker", "{token, group, dst_rank}",
                              "bool (shards pushed over the collective "
                              "group, mesh order)"),

    # ---- per-node agent (ref: agent_manager.h + runtime_env_agent) ----
    "BuildRuntimeEnv": _m("agent", "{wire}", "{ok}|{ok: False, error}"),
    "AgentListLogs": _m("agent", "{}", "[{filename, size}]"),
    "AgentReadLog": _m("agent", "{filename, offset?, tail?, max_bytes?}",
                       "{data, next_offset, eof}|{error}"),
    "AgentMetrics": _m("agent", "{}", "{os gauges}"),
    "AgentStats": _m("agent", "{}", "{env_builds, log_reads, "
                              "profiles_captured, device, ...}"),
    "AgentDeviceStats": _m("agent", "{}",
                           "[{name, type, value, tags, description}]"),
    "AgentProfile": _m("agent", "{duration_s?}",
                       "{trace_dir, archive, duration_s}|{error}"),
    "GetAgentInfo": _m("node", "{}", "{address, alive, restarts}"),

    # ---- store service (shared-store HA) ------------------------------
    "StorePut": _m("store", "{table, key, value}", "bool"),
    "StoreGet": _m("store", "{table, key}", "bytes|None"),
    "StoreDelete": _m("store", "{table, key}", "bool"),
    "StoreLoadTable": _m("store", "{table}", "{key: value}"),
    "LeaseAcquire": _m("store", "{name, owner, ttl}",
                       "bool (HA leader lease)"),
    "LeaseRenew": _m("store", "{name, owner, ttl}", "bool"),
    "LeaseRelease": _m("store", "{name, owner}", "bool"),
    "LeaseInfo": _m("store", "{name}", "{owner, expires_at}|None"),
}


# ---------------------------------------------------------------- HA split
#
# The replicated-GCS read/write classification (the HA analogue of the
# reference's GCS-FT blueprint): a GCS method is exactly one of
#
# * a FOLLOWER READ — servable by any replica from its store-synced
#   tables (staleness bounded by gcs_ha_sync_period_s); the client
#   router fans these out to standbys so read load scales with them;
# * a RING WRITE — a high-churn bounded-ring ingestion (task / step /
#   span events) accepted on ANY replica, sharded by producer key
#   client-side and merged at query time (the matching *Get / ListTasks
#   family accepts a ``local_only`` payload key for the merge fan-out);
# * everything else — a MUTATION, leader-only: a follower receiving one
#   replies with a typed NotLeaderError redirect.
#
# Follower-side enforcement and client-side routing both read THESE
# sets, so the split cannot drift between server and router.

GCS_FOLLOWER_READS = frozenset({
    "GetAllNodes", "ListNodes", "ClusterResources",
    "AvailableResources", "KVGet", "KVKeys",
    "ListActors", "ListObjects", "ListPlacementGroups",
    "ListVirtualClusters", "ListJobs",
    "MetricsGet", "InsightGet",
    "TaskEventsGet", "StepEventsGet", "SpanEventsGet",
    "CpuProfileGet", "GetScaleStats",
    "ListTasks", "GetTask", "SummarizeTasks",
    "GetHaView",
})

GCS_RING_WRITES = frozenset({
    "TaskEventsAdd", "StepEventsAdd", "SpanEventsAdd",
    "CpuProfileAdd",
})


def gcs_methods() -> frozenset:
    return frozenset(m for m, e in METHODS.items()
                     if e["service"].split("|")[0] == "gcs")


def gcs_mutations() -> frozenset:
    """Leader-only methods: the GCS surface minus follower reads and
    any-replica ring writes."""
    return gcs_methods() - GCS_FOLLOWER_READS - GCS_RING_WRITES
