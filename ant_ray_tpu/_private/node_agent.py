"""Per-node agent process (ref: the reference's per-node agents —
dashboard agent `dashboard/agent.py:24`, runtime-env agent
`runtime_env/agent/runtime_env_agent.py:167`, metrics agent
`_private/metrics_agent.py` — spawned and supervised by the raylet's
AgentManager, `src/ray/raylet/agent_manager.h`).

One process covers the three agent roles:

* **runtime-env builds** — package extraction and pip/uv/conda env
  materialization run HERE, so a slow or crashing build can never take
  the node daemon's event loop or process down with it;
* **log serving** — ListLogs/ReadLog over the session dir (the daemon
  keeps its own copies of these routes for back-compat; the dashboard
  may talk to either);
* **node metrics** — OS-level gauges (load, memory, disk) for the
  head's metrics aggregation;
* **device telemetry** — per-device HBM stats
  (observability/device_stats.py) served on demand and published into
  the GCS metrics table on an interval, and on-demand XLA trace
  capture (``AgentProfile`` ← dashboard ``POST /api/profile``):
  ``jax.profiler.trace`` for the requested duration, archived into the
  session log dir so the existing log routes list and serve it.

The daemon restarts a dead agent with backoff and falls back to
in-process builds while the agent is down — agents are an isolation
upgrade, never a single point of failure.
"""

from __future__ import annotations

import asyncio
import logging
import os
import threading
import time

from ant_ray_tpu._private.config import global_config
from ant_ray_tpu._private.protocol import ClientPool, RpcServer

logger = logging.getLogger(__name__)


class NodeAgent:
    def __init__(self, session_dir: str, gcs_address: str,
                 host: str = "127.0.0.1", port: int = 0):
        self._session_dir = session_dir
        self._gcs_address = gcs_address
        self._server = RpcServer(host, port)
        self._clients = ClientPool()
        self.stats = {"env_builds": 0, "env_build_failures": 0,
                      "log_reads": 0, "profiles_captured": 0,
                      "started_at": time.time()}
        self.address = ""
        self._profiling = threading.Lock()
        self._stop_publish = threading.Event()
        self._publish_thread: threading.Thread | None = None
        self._cpu_profiler = None

    def start(self) -> str:
        self._server.routes({
            "BuildRuntimeEnv": self._build_runtime_env,
            "AgentListLogs": self._list_logs,
            "AgentReadLog": self._read_log,
            "AgentMetrics": self._metrics,
            "AgentStats": self._get_stats,
            "AgentDeviceStats": self._device_stats,
            "AgentProfile": self._profile,
            "Ping": self._ping,
        })
        self.address = self._server.start()
        interval = global_config().device_stats_interval_s
        if interval > 0:
            self._publish_thread = threading.Thread(
                target=self._publish_device_stats_loop, args=(interval,),
                daemon=True, name="agent-device-stats")
            self._publish_thread.start()
        # Continuous CPU profiling: the agent publishes through its own
        # blocking GCS client (the publish runs on the sampler thread,
        # never the io loop).
        from ant_ray_tpu.observability import cpu_profiler  # noqa: PLC0415

        self._cpu_profiler = None
        if global_config().cpu_profile_hz > 0:
            def _publish_profile(record, agent=self):
                agent._clients.get(agent._gcs_address).call(
                    "CpuProfileAdd", {"records": [record]}, timeout=5)

            def _publish_metric(payload, agent=self):
                agent._clients.get(agent._gcs_address).call(
                    "MetricRecord", payload, timeout=5)

            self._cpu_profiler = cpu_profiler.CpuProfiler(
                "agent", publish_fn=_publish_profile,
                metric_fn=_publish_metric).start()
        return self.address

    def stop(self) -> None:
        self._stop_publish.set()
        if self._cpu_profiler is not None:
            profiler, self._cpu_profiler = self._cpu_profiler, None
            profiler.stop(final_publish=False)
        if self._publish_thread is not None:
            self._publish_thread.join(timeout=2.0)
        self._server.stop()
        self._clients.close_all()

    async def _ping(self, _payload):
        return "pong"

    async def _get_stats(self, _payload):
        from ant_ray_tpu.observability import device_stats  # noqa: PLC0415

        out = dict(self.stats)
        # device_memory_stats may import jax (seconds, once) — keep the
        # agent's event loop responsive while it does.
        out["device"] = await asyncio.get_running_loop().run_in_executor(
            None, device_stats.device_memory_stats)
        return out

    # ---------------------------------------------------- runtime envs

    async def _build_runtime_env(self, payload):
        """Materialize a runtime env in THIS process — the daemon
        delegates here so builds are isolated from its event loop (ref:
        runtime_env_agent.py:167).  The build sequence itself is the
        shared runtime_env.materialize (identical to the daemon's
        in-process fallback)."""
        from ant_ray_tpu._private import runtime_env as renv  # noqa: PLC0415

        gcs = self._clients.get(self._gcs_address)

        async def kv_get(key):
            return await gcs.call_async("KVGet", {"key": key},
                                        timeout=60)

        try:
            await renv.materialize(payload.get("wire"),
                                   self._session_dir, kv_get)
            self.stats["env_builds"] += 1
            return {"ok": True}
        except Exception as e:  # noqa: BLE001 — reported to the daemon
            self.stats["env_build_failures"] += 1
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    # ------------------------------------------------------------ logs

    async def _list_logs(self, _payload):
        from ant_ray_tpu._private import log_serving  # noqa: PLC0415

        return log_serving.list_logs(self._session_dir)

    async def _read_log(self, payload):
        from ant_ray_tpu._private import log_serving  # noqa: PLC0415

        self.stats["log_reads"] += 1
        return log_serving.read_log(self._session_dir, payload)

    # --------------------------------------------------------- metrics

    async def _metrics(self, _payload):
        """OS-level node gauges (the metrics-agent role)."""
        gauges: dict[str, float] = {}
        try:
            load1, load5, load15 = os.getloadavg()
            gauges.update({"load_1m": load1, "load_5m": load5,
                           "load_15m": load15})
        except OSError:
            pass
        try:
            fields = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    key, _, rest = line.partition(":")
                    fields[key.strip()] = int(rest.strip().split()[0])
            gauges["mem_total_kb"] = float(fields.get("MemTotal", 0))
            gauges["mem_available_kb"] = float(
                fields.get("MemAvailable", 0))
        except (OSError, ValueError, IndexError):
            pass
        try:
            stat = os.statvfs(self._session_dir)
            gauges["disk_free_bytes"] = float(stat.f_bavail * stat.f_frsize)
        except OSError:
            pass
        return gauges

    # ------------------------------------------------ device telemetry

    async def _device_stats(self, _payload):
        """Per-device HBM gauges in the node-metrics wire shape
        (observability/device_stats.py; CPU backends yield [])."""
        from ant_ray_tpu.observability import device_stats  # noqa: PLC0415

        return await asyncio.get_running_loop().run_in_executor(
            None, device_stats.device_stats_gauges)

    def _publish_device_stats_loop(self, interval: float) -> None:
        """Push HBM gauges into the GCS metrics table on an interval so
        /metrics carries art_device_hbm_* without a scrape hop.  Waits
        one full interval before the first publish — the jax import
        this forces must not slow agent startup."""
        from ant_ray_tpu.observability import device_stats  # noqa: PLC0415

        # Tag with this node's identity: different nodes' chips must not
        # collide on one series, and the GCS prunes node-tagged series
        # when the node dies (stale-gauge expiry) — matching the short
        # id the dashboard's live scrape stamps.
        node_id = os.environ.get("ART_NODE_ID", "")[:12]
        while not self._stop_publish.wait(interval):
            try:
                gauges = device_stats.device_stats_gauges()
            except Exception:  # noqa: BLE001 — stay alive, retry later
                continue
            gcs = self._clients.get(self._gcs_address)
            for g in gauges:
                if node_id:
                    g.setdefault("tags", {})["node_id"] = node_id
                try:
                    gcs.call("MetricRecord", g, timeout=5)
                except Exception:  # noqa: BLE001 — head restarting
                    break

    async def _profile(self, payload):
        """On-demand XLA trace capture (dashboard POST /api/profile →
        daemon GetAgentInfo → here).  Runs ``jax.profiler.trace`` for
        ``duration_s``, then archives the trace tree into the session
        log dir — a single .tar.gz the existing ListLogs/ReadLog routes
        serve.  One capture at a time (the XLA profiler is a process
        singleton)."""
        duration = max(0.05, min(
            float((payload or {}).get("duration_s", 2.0)), 300.0))
        return await asyncio.get_running_loop().run_in_executor(
            None, self._capture_trace, duration)

    def _capture_trace(self, duration_s: float) -> dict:
        if not self._profiling.acquire(blocking=False):
            return {"error": "a trace capture is already in progress"}
        try:
            try:
                from ant_ray_tpu._private.jax_utils import import_jax  # noqa: PLC0415

                jax = import_jax()
            except Exception as e:  # noqa: BLE001 — report, don't crash
                return {"error": f"jax unavailable: {e}"}
            import tarfile  # noqa: PLC0415

            from ant_ray_tpu._private import log_serving  # noqa: PLC0415

            stamp = time.strftime("%Y%m%d-%H%M%S")
            trace_dir = os.path.join(self._session_dir, "profiles",
                                     f"xla-{stamp}-{os.getpid()}")
            os.makedirs(trace_dir, exist_ok=True)
            try:
                with jax.profiler.trace(trace_dir):
                    time.sleep(duration_s)
            except Exception as e:  # noqa: BLE001
                return {"error":
                        f"trace capture failed: {type(e).__name__}: {e}"}
            logs_dir = log_serving.logs_dir(self._session_dir)
            os.makedirs(logs_dir, exist_ok=True)
            archive = f"xla-trace-{stamp}-{os.getpid()}.tar.gz"
            with tarfile.open(os.path.join(logs_dir, archive),
                              "w:gz") as tar:
                tar.add(trace_dir, arcname=os.path.basename(trace_dir))
            self.stats["profiles_captured"] += 1
            return {"trace_dir": trace_dir, "archive": archive,
                    "duration_s": duration_s}
        finally:
            self._profiling.release()


def main():  # pragma: no cover — exercised via subprocess in tests
    import argparse
    import signal

    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--monitor-pid", type=int, default=0)
    args = parser.parse_args()

    logging.basicConfig(
        level=global_config().log_level,
        format="[agent %(levelname)s %(asctime)s] %(message)s")
    agent = NodeAgent(args.session_dir, args.gcs_address)
    agent.start()
    print(f"AGENT_READY {agent.address}", flush=True)

    stop = False

    def _term(*_a):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop:
        time.sleep(0.2)
        if args.monitor_pid and not os.path.exists(
                f"/proc/{args.monitor_pid}"):
            break
    agent.stop()
    os._exit(0)


if __name__ == "__main__":
    main()
