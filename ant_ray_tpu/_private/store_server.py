"""Standalone GCS-table store service — the shared-store HA backend.

Role of the reference's Redis-backed GCS persistence (ref:
src/ray/gcs/store_client/redis_store_client.h + the ant fork's
Redis-lease leader election, python/ray/ha/redis_leader_selector.py:90):
the head's tables live OUTSIDE the head machine, so a standby head on
another machine can restore the cluster after the primary dies.
Redesigned for this stack: a small asyncio RPC service (the framework's
own protocol, no Redis dependency) hosting the sqlite store plus a
compare-and-swap lease table for cross-machine leader election.

Run:  python -m ant_ray_tpu._private.store_server --port P --path DB
Point heads at it with ``--store art-store://host:port``.
"""

from __future__ import annotations

import logging
import os
import time

from ant_ray_tpu._private.protocol import RpcServer
from ant_ray_tpu._private.store_client import SqliteStoreClient

logger = logging.getLogger(__name__)


class StoreServer:
    """RPC front of a SqliteStoreClient + TTL leases (leader election)."""

    def __init__(self, path: str, host: str = "127.0.0.1", port: int = 0):
        self._store = SqliteStoreClient(path)
        self._server = RpcServer(host, port)
        # lease name -> {"holder", "token", "expires_at"}
        self._leases: dict[str, dict] = {}
        self.address = ""

    def start(self) -> str:
        self._server.routes({
            "StorePut": self._put,
            "StoreGet": self._get,
            "StoreDelete": self._delete,
            "StoreLoadTable": self._load_table,
            "LeaseAcquire": self._lease_acquire,
            "LeaseRenew": self._lease_renew,
            "LeaseRelease": self._lease_release,
            "LeaseInfo": self._lease_info,
            "Ping": self._ping,
        })
        self.address = self._server.start()
        return self.address

    def stop(self) -> None:
        self._server.stop()
        self._store.close()

    # ------------------------------------------------------------ tables

    async def _put(self, payload):
        self._store.put(payload["table"], payload["key"],
                        payload["value"])
        return True

    async def _get(self, payload):
        return self._store.get(payload["table"], payload["key"])

    async def _delete(self, payload):
        self._store.delete(payload["table"], payload["key"])
        return True

    async def _load_table(self, payload):
        return self._store.load_table(payload["table"])

    async def _ping(self, _payload):
        return "pong"

    # ------------------------------------------------------------ leases
    # Compare-and-swap TTL leases, the Redis SET-NX-PX election pattern
    # (ref: redis_leader_selector.py) — single-threaded on the io loop,
    # so acquire/renew are naturally atomic.

    def _live_lease(self, name: str) -> dict | None:
        lease = self._leases.get(name)
        if lease is None or lease["expires_at"] < time.monotonic():
            return None
        return lease

    async def _lease_acquire(self, payload):
        name = payload["name"]
        lease = self._live_lease(name)
        if lease is not None and lease["token"] != payload["token"]:
            return {"acquired": False, "holder": lease["holder"]}
        self._leases[name] = {
            "holder": payload["holder"],
            "token": payload["token"],
            "expires_at": time.monotonic() + payload["ttl"],
        }
        return {"acquired": True}

    async def _lease_renew(self, payload):
        name = payload["name"]
        lease = self._live_lease(name)
        if lease is None or lease["token"] != payload["token"]:
            return {"renewed": False}   # expired or usurped: fenced out
        lease["expires_at"] = time.monotonic() + payload["ttl"]
        return {"renewed": True}

    async def _lease_release(self, payload):
        lease = self._leases.get(payload["name"])
        if lease is not None and lease["token"] == payload["token"]:
            del self._leases[payload["name"]]
        return True

    async def _lease_info(self, payload):
        lease = self._live_lease(payload["name"])
        if lease is None:
            return None
        return {"holder": lease["holder"], "token": lease["token"]}


def main():  # pragma: no cover — exercised via subprocess in tests
    import argparse
    import signal

    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--path", required=True)
    parser.add_argument("--monitor-pid", type=int, default=0)
    args = parser.parse_args()
    logging.basicConfig(level="INFO",
                        format="[store %(levelname)s %(asctime)s] "
                               "%(message)s")
    server = StoreServer(args.path, port=args.port)
    server.start()
    print(f"STORE_READY {server.address}", flush=True)

    stop = False

    def _term(*_a):
        nonlocal stop
        stop = True

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop:
        time.sleep(0.2)
        if args.monitor_pid and not os.path.exists(
                f"/proc/{args.monitor_pid}"):
            break
    os._exit(0)


if __name__ == "__main__":
    main()
