"""Global worker singleton + runtime interface + local-mode runtime.

The ``Worker`` here plays the role of the reference's per-process worker
singleton (ref: python/ray/_private/worker.py) and delegates to a pluggable
``CoreRuntime`` — the analogue of the C++ CoreWorker
(ref: src/ray/core_worker/core_worker.h:167).  Two runtimes exist:

* ``LocalModeRuntime`` — single-process synchronous execution for unit
  testing without daemons (ref: core_worker.cc:3256 ExecuteTaskLocalMode).
* ``ClusterRuntime`` (``ant_ray_tpu/_private/core.py``) — the real
  multiprocess path: GCS head + node daemons + worker processes.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Sequence

from ant_ray_tpu import exceptions
from ant_ray_tpu._private.ids import ActorID, JobID, ObjectID, TaskID, _PutIndexCounter
from ant_ray_tpu._private.task_options import ActorOptions, TaskOptions
from ant_ray_tpu.object_ref import ObjectRef

LOCAL_MODE = "local"
CLUSTER_MODE = "cluster"


class CoreRuntime:
    """Interface every runtime implements (mirrors CoreWorker's surface)."""

    def submit_task(self, remote_function, args, kwargs,
                    options: TaskOptions) -> ObjectRef | list[ObjectRef]:
        raise NotImplementedError

    def create_actor(self, actor_class, args, kwargs, options: ActorOptions):
        raise NotImplementedError

    def submit_actor_task(self, handle, method_name, args, kwargs,
                          options: TaskOptions):
        raise NotImplementedError

    def put(self, value: Any) -> ObjectRef:
        raise NotImplementedError

    def get(self, refs: Sequence[ObjectRef], timeout: float | None) -> list:
        raise NotImplementedError

    def wait(self, refs, num_returns: int, timeout: float | None,
             fetch_local: bool):
        raise NotImplementedError

    def get_actor(self, name: str, namespace: str | None):
        raise NotImplementedError

    def kill_actor(self, handle, no_restart: bool = True):
        raise NotImplementedError

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True):
        raise NotImplementedError

    def cluster_resources(self) -> dict:
        raise NotImplementedError

    def available_resources(self) -> dict:
        raise NotImplementedError

    def nodes(self) -> list[dict]:
        raise NotImplementedError

    def shutdown(self):
        raise NotImplementedError


def resolve_value(value, ref_resolver):
    """Resolve a possibly-ObjectRef top-level argument (ref semantics: only
    top-level args are fetched; nested refs are passed through)."""
    if isinstance(value, ObjectRef):
        return ref_resolver(value)
    return value


def maybe_raise(value):
    if isinstance(value, exceptions.TaskError):
        raise value
    if isinstance(value, exceptions.ArtError):
        raise value
    return value


class LocalModeRuntime(CoreRuntime):
    """Synchronous single-process execution: tasks run eagerly at submission,
    objects live in a dict; no daemons, no serialization round-trips (but
    results of failed tasks are stored as TaskError to match cluster-mode
    error lineage)."""

    def __init__(self, job_id: JobID):
        self._job_id = job_id
        self._objects: dict[ObjectID, Any] = {}
        self._actors: dict[ActorID, Any] = {}
        self._actor_meta: dict[ActorID, dict] = {}
        self._named_actors: dict[tuple[str, str], ActorID] = {}
        self._put_counter = _PutIndexCounter()
        self._driver_task_id = TaskID.for_driver_task(job_id)
        from ant_ray_tpu._lint.lockcheck import make_rlock  # noqa: PLC0415

        self._lock = make_rlock("worker.state")

    # ---- helpers

    def _store(self, task_id: TaskID, values: list) -> list[ObjectRef]:
        refs = []
        for i, v in enumerate(values):
            oid = ObjectID.for_task_return(task_id, i)
            self._objects[oid] = v
            refs.append(ObjectRef(oid, owner_address="local"))
        return refs

    def _resolve(self, ref: ObjectRef):
        if ref.id not in self._objects:
            raise exceptions.ObjectLostError(ref.id, "not found in local mode")
        return maybe_raise(self._objects[ref.id])

    def _resolve_args(self, args, kwargs):
        args = [resolve_value(a, self._resolve) for a in args]
        kwargs = {k: resolve_value(v, self._resolve) for k, v in kwargs.items()}
        return args, kwargs

    def _pack(self, result, num_returns: int) -> list:
        if num_returns == 1:
            return [result]
        return list(result)

    # ---- tasks

    def submit_task(self, remote_function, args, kwargs, options: TaskOptions):
        task_id = TaskID.for_normal_task(self._job_id)
        num_returns = options.num_returns
        if num_returns == "streaming":
            # Local mode executes eagerly; the generator surface is kept
            # so user code is portable.
            rargs, rkwargs = self._resolve_args(args, kwargs)
            result = remote_function.function(*rargs, **rkwargs)
            return iter(self._store(task_id, list(result)))
        try:
            rargs, rkwargs = self._resolve_args(args, kwargs)
            result = remote_function.function(*rargs, **rkwargs)
            values = self._pack(result, num_returns)
        except Exception as e:  # noqa: BLE001 — stored as task error
            err = exceptions.TaskError.from_exception(
                remote_function.function_name, e)
            values = [err] * num_returns
        refs = self._store(task_id, values)
        return refs[0] if num_returns == 1 else refs

    # ---- actors

    def create_actor(self, actor_class, args, kwargs, options: ActorOptions):
        from ant_ray_tpu.actor import ActorHandle  # noqa: PLC0415

        namespace = options.namespace or "default"
        if options.name:
            with self._lock:
                existing = self._named_actors.get((namespace, options.name))
                if existing is not None:
                    if options.get_if_exists:
                        meta = self._actor_meta[existing]
                        return ActorHandle(
                            existing, meta["class_name"], meta["method_names"],
                            method_num_returns=meta["method_num_returns"])
                    raise ValueError(
                        f"Actor name {options.name!r} already taken")
        actor_id = ActorID.of(self._job_id)
        rargs, rkwargs = self._resolve_args(args, kwargs)
        instance = actor_class.cls(*rargs, **rkwargs)
        with self._lock:
            self._actors[actor_id] = instance
            self._actor_meta[actor_id] = {
                "class_name": actor_class._class_name,
                "method_names": actor_class.method_names(),
                "method_num_returns": actor_class.method_num_returns(),
            }
            if options.name:
                self._named_actors[(namespace, options.name)] = actor_id
        return ActorHandle(actor_id, actor_class._class_name,
                           actor_class.method_names(),
                           method_num_returns=actor_class.method_num_returns())

    def submit_actor_task(self, handle, method_name, args, kwargs,
                          options: TaskOptions):
        task_id = TaskID.for_actor_task(handle.actor_id)
        num_returns = options.num_returns
        instance = self._actors.get(handle.actor_id)
        try:
            if instance is None:
                raise exceptions.ActorDiedError(handle.actor_id, "killed")
            rargs, rkwargs = self._resolve_args(args, kwargs)
            method = getattr(instance, method_name)
            result = method(*rargs, **rkwargs)
            # inspect, not asyncio: on Python < 3.12 asyncio.iscoroutine
            # also matches plain generators (streaming actor methods).
            if inspect.iscoroutine(result):
                result = asyncio.run(result)
            values = self._pack(result, num_returns)
        except exceptions.ActorDiedError:
            raise
        except Exception as e:  # noqa: BLE001
            err = exceptions.ActorError.from_exception(
                f"{handle.class_name}.{method_name}", e)
            values = [err] * num_returns
        refs = self._store(task_id, values)
        return refs[0] if num_returns == 1 else refs

    def get_actor(self, name: str, namespace: str | None):
        from ant_ray_tpu.actor import ActorHandle  # noqa: PLC0415

        key = (namespace or "default", name)
        actor_id = self._named_actors.get(key)
        if actor_id is None:
            raise ValueError(f"Failed to look up actor {name!r}")
        meta = self._actor_meta[actor_id]
        return ActorHandle(actor_id, meta["class_name"], meta["method_names"],
                           method_num_returns=meta["method_num_returns"])

    def kill_actor(self, handle, no_restart: bool = True):
        with self._lock:
            self._actors.pop(handle.actor_id, None)
            for key, aid in list(self._named_actors.items()):
                if aid == handle.actor_id:
                    del self._named_actors[key]

    def cancel(self, ref, force=False, recursive=True):
        pass  # local mode tasks already completed at submission

    # ---- objects

    def put(self, value: Any) -> ObjectRef:
        idx = self._put_counter.next(self._driver_task_id)
        oid = ObjectID.for_task_return(self._driver_task_id, idx & 0xFFFF_FFFF)
        self._objects[oid] = value
        return ObjectRef(oid, owner_address="local")

    def get(self, refs: Sequence[ObjectRef], timeout: float | None) -> list:
        return [self._resolve(r) for r in refs]

    def wait(self, refs, num_returns, timeout, fetch_local):
        ready = [r for r in refs if r.id in self._objects]
        not_ready = [r for r in refs if r.id not in self._objects]
        return ready[:num_returns], ready[num_returns:] + not_ready

    # ---- cluster info

    def cluster_resources(self):
        import os  # noqa: PLC0415

        return {"CPU": float(os.cpu_count() or 1)}

    def available_resources(self):
        return self.cluster_resources()

    def nodes(self):
        return [{"NodeID": "local", "Alive": True,
                 "Resources": self.cluster_resources()}]

    def shutdown(self):
        self._objects.clear()
        self._actors.clear()
        self._named_actors.clear()


class Worker:
    """Per-process singleton fronting the active runtime."""

    def __init__(self):
        self.mode: str | None = None
        self.runtime: CoreRuntime | None = None
        self.job_id: JobID | None = None
        self.current_actor_id: ActorID | None = None
        from ant_ray_tpu._lint.lockcheck import make_lock  # noqa: PLC0415

        self._lock = make_lock("worker.connect")

    @property
    def connected(self) -> bool:
        return self.runtime is not None

    def _check_connected(self):
        if self.runtime is None:
            from ant_ray_tpu._private import auto_init  # noqa: PLC0415

            auto_init.auto_init()
        if self.runtime is None:
            raise RuntimeError(
                "ant_ray_tpu.init() must be called before using the API")

    def submit_task(self, remote_function, args, kwargs, options):
        self._check_connected()
        return self.runtime.submit_task(remote_function, args, kwargs, options)

    def create_actor(self, actor_class, args, kwargs, options):
        self._check_connected()
        return self.runtime.create_actor(actor_class, args, kwargs, options)

    def submit_actor_task(self, handle, method_name, args, kwargs, options):
        self._check_connected()
        return self.runtime.submit_actor_task(
            handle, method_name, args, kwargs, options)

    def put(self, value):
        self._check_connected()
        return self.runtime.put(value)

    def get(self, refs, timeout=None):
        self._check_connected()
        single = isinstance(refs, ObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ObjectRef):
                raise TypeError(
                    f"get() expects ObjectRef(s), got {type(r).__name__}")
        values = self.runtime.get(ref_list, timeout)
        return values[0] if single else values

    async def get_async(self, ref: ObjectRef):
        # Round 1: thread-offloaded blocking get (async actors can await refs).
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, lambda: self.get(ref))

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        self._check_connected()
        if len(refs) == 0:
            return [], []
        if num_returns <= 0 or num_returns > len(refs):
            raise ValueError(
                f"num_returns must be in [1, {len(refs)}], got {num_returns}")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ready, not_ready = self.runtime.wait(
                refs, num_returns, timeout, fetch_local)
            if len(ready) >= num_returns or (
                    deadline is not None and time.monotonic() >= deadline):
                # Contract (matches the reference): at most num_returns
                # refs come back ready; surplus ready refs stay in the
                # continuation list so `done, refs = wait(refs, 1)` loops
                # never drop results.
                if len(ready) > num_returns:
                    not_ready = ready[num_returns:] + not_ready
                    ready = ready[:num_returns]
                return ready, not_ready
            time.sleep(0.005)

    def exit_current_actor(self):
        raise SystemExit(0)

    def shutdown(self):
        with self._lock:
            try:
                if self.runtime is not None:
                    self.runtime.shutdown()
            finally:
                # The disconnect must stick even when a teardown step
                # throws (a dying cluster races its own disconnect):
                # a runtime left behind here turns the NEXT init() in
                # this process into "init() called twice".
                self.runtime = None
                self.mode = None
                self.job_id = None


global_worker = Worker()
