"""Session-log serving, shared by the node daemon (back-compat routes)
and the per-node agent (ref: the reference's log agent endpoints —
dashboard/agent.py:24).  One implementation so the traversal guard and
read caps can never diverge between the two servers."""

from __future__ import annotations

import os


def logs_dir(session_dir: str) -> str:
    return os.path.join(session_dir, "logs")


def list_logs(session_dir: str) -> list[dict]:
    directory = logs_dir(session_dir)
    if not os.path.isdir(directory):
        return []
    out = []
    for name in sorted(os.listdir(directory)):
        try:
            out.append({"filename": name,
                        "size": os.path.getsize(
                            os.path.join(directory, name))})
        except OSError:
            continue
    return out


def read_log(session_dir: str, payload: dict) -> dict:
    name = os.path.basename(payload["filename"])  # no traversal
    path = os.path.join(logs_dir(session_dir), name)
    max_bytes = min(int(payload.get("max_bytes", 65536)), 4 << 20)
    tail = payload.get("tail")
    try:
        size = os.path.getsize(path)
        offset = int(payload.get("offset", 0))
        if tail is not None:  # last N bytes
            offset = max(0, size - int(tail))
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(max_bytes)
        return {"data": data, "offset": offset,
                "next_offset": offset + len(data),
                "eof": offset + len(data) >= size}
    except OSError as e:
        return {"error": str(e)}
