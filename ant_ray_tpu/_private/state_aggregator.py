"""Cluster-state aggregation joins (ref: the reference's
``state_aggregator.py`` behind ``ray.util.state`` — the one place that
merges per-source truths into the operator view).

Two joins live here so every surface renders the SAME truth:

* :func:`list_objects_joined` — the GCS object directory (locations +
  owner attribution) joined with per-daemon arena stats (sizes, pins,
  storage tier, chunk-cache residency).  Feeds ``/api/objects``,
  ``art list objects``, and the memory report below.
* :func:`build_memory_report` — the ``ray memory`` analog: per-node
  usage, top-N objects by size with owner/holders/pin state (and the
  creation callsite when ``record_object_callsite`` was on), plus leak
  candidates (owner unreachable, or owner alive with no live
  reference).

Everything is duck-typed on ``gcs`` (an RpcClient for the head) and
``clients`` (a ClientPool) so the dashboard process, the operator CLI,
and a connected driver all share one implementation — none of them
needs a worker runtime.
"""

from __future__ import annotations


def _alive_nodes(gcs) -> dict[str, str]:
    """node_id hex -> daemon RPC address, alive nodes only."""
    infos = gcs.call("GetAllNodes", retries=3)
    return {info.node_id.hex(): info.address
            for info in infos.values() if info.alive}


def _daemon_object_stats(clients, nodes: dict[str, str],
                         timeout: float = 5.0) -> dict[str, dict]:
    """node_id hex -> ListObjectStats reply; nodes mid-death are
    skipped (their holdings vanish with them)."""
    out = {}
    for node_id, address in nodes.items():
        try:
            out[node_id] = clients.get(address).call(
                "ListObjectStats", {}, timeout=timeout)
        except Exception:  # noqa: BLE001 — node mid-death
            continue
    return out


def list_objects_joined(gcs, clients, per_node: dict | None = None
                        ) -> list[dict]:
    """Directory ∪ per-daemon residency, one record per object.
    ``per_node`` lets a caller that already swept the daemons (the
    memory report) reuse its snapshot instead of sweeping twice."""
    directory = gcs.call("ListObjects", retries=3) or []
    if per_node is None:
        per_node = _daemon_object_stats(clients, _alive_nodes(gcs))

    # object_id -> {node_id -> daemon-side stats}
    residency: dict[str, dict[str, dict]] = {}
    for node_id, reply in per_node.items():
        for entry in reply.get("objects", ()):
            residency.setdefault(entry["object_id"], {})[node_id] = entry

    out = []
    seen = set()
    for record in directory:
        oid = record["object_id"]
        seen.add(oid)
        copies = residency.get(oid, {})
        out.append(_joined_record(oid, record, copies))
    # Daemon-resident objects the directory doesn't (yet / anymore)
    # know — mid-registration or retraction lag; the bytes are real,
    # so the view includes them.
    for oid, copies in residency.items():
        if oid not in seen:
            out.append(_joined_record(oid, {}, copies))
    return out


def _joined_record(oid: str, directory_record: dict,
                   copies: dict[str, dict]) -> dict:
    size = max((c["size"] for c in copies.values()), default=None)
    return {
        "object_id": oid,
        "size": size,
        "owner": directory_record.get("owner"),
        "callsite": directory_record.get("callsite"),
        "locations": sorted(set(directory_record.get("locations") or ())
                            | set(copies)),
        "pinned": any(c.get("pins", 0) > 0 for c in copies.values()),
        "copies": [
            {"node_id": node_id, "size": c["size"],
             "pins": c.get("pins", 0), "tier": c.get("tier"),
             "chunk_cache_bytes": c.get("chunk_cache_bytes", 0)}
            for node_id, c in sorted(copies.items())
        ],
    }


def _owner_ref_info(clients, objects: list[dict],
                    timeout: float = 5.0) -> dict[str, dict | None | str]:
    """object_id -> owner-side refcounts, ``None`` (owner holds no
    reference state), or ``"owner_unreachable"``.  One RPC per OWNER,
    not per object."""
    by_owner: dict[str, list[str]] = {}
    for record in objects:
        if record.get("owner"):
            by_owner.setdefault(record["owner"], []).append(
                record["object_id"])
    out: dict[str, dict | None | str] = {}
    for owner, oids in by_owner.items():
        try:
            reply = clients.get(owner).call(
                "GetOwnedRefInfo", {"object_ids": oids},
                timeout=timeout)
        except Exception:  # noqa: BLE001 — owner process gone
            for oid in oids:
                out[oid] = "owner_unreachable"
            continue
        for oid in oids:
            out[oid] = reply.get(oid)
    return out


def build_memory_report(gcs, clients, top_n: int = 20) -> dict:
    """The ``ray memory`` analog (see module docstring)."""
    nodes = _alive_nodes(gcs)
    per_node = _daemon_object_stats(clients, nodes)
    objects = list_objects_joined(gcs, clients, per_node=per_node)
    ref_info = _owner_ref_info(clients, objects)

    for record in objects:
        info = ref_info.get(record["object_id"])
        if record.get("owner") is None:
            record["refs"] = None
            record["leak"] = None   # no attribution — can't judge
        elif info == "owner_unreachable":
            record["refs"] = None
            record["leak"] = "owner_dead"
        elif info is None and not record["pinned"]:
            # None is the owner's explicit "I hold NO reference state
            # for this id" (an all-zero count dict instead means the
            # owner still caches the value — alive, not a leak).  A
            # read-pinned copy has a live zero-copy reader even then.
            record["refs"] = None
            record["leak"] = "no_live_reference"
        else:
            record["refs"] = info
            record["leak"] = None

    objects.sort(key=lambda r: r["size"] or 0, reverse=True)
    node_rows = []
    for node_id, address in sorted(nodes.items()):
        reply = per_node.get(node_id)
        store = (reply or {}).get("store", {})
        node_rows.append({
            "node_id": node_id, "address": address,
            "used": store.get("used"),
            "capacity": store.get("capacity"),
            "spilled": store.get("spilled"),
            "objects": len((reply or {}).get("objects", ())),
        })
    return {
        "nodes": node_rows,
        "objects": objects[:max(0, int(top_n))],
        "leak_candidates": [r for r in objects if r.get("leak")],
        "totals": {
            "objects": len(objects),
            "bytes": sum(r["size"] or 0 for r in objects),
            "pinned_objects": sum(1 for r in objects if r["pinned"]),
            "chunk_cache_bytes": sum(
                c["chunk_cache_bytes"]
                for r in objects for c in r["copies"]),
        },
    }
