"""Wire-level task/actor specifications (ref: src/ray/common/task/task_spec.h
semantics — everything a worker needs to execute a task, self-contained)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ant_ray_tpu._private.ids import ActorID, JobID, NodeID, TaskID


class PromotedArgs:
    """Marker for task args promoted to the object plane: above
    max_inline_object_size the (args, kwargs) blob is put into plasma and
    the spec carries only this ref (ref: max_direct_call_object_size —
    large args never travel inside the control-plane RPC frame)."""

    __slots__ = ("ref",)

    def __init__(self, ref):
        self.ref = ref


@dataclass
class TaskSpec:
    """One task/actor-call submission, self-contained.

    Wire forms: the pickled positional tuple (``__reduce__`` below —
    the universal transport), and the hot-frame split
    (``_private/hotframe.py``): fields invariant per call shape
    (``TEMPLATE_FIELDS``) are interned once per connection, varying
    fields (``CALL_FIELDS``) ride each call struct-packed, and
    ``args_payload`` travels as raw bytes outside pickle entirely.
    Adding a field here means deciding which side of that split it
    lands on — the artlint frame-schema snapshot makes the choice
    explicit and append-only."""

    task_id: TaskID
    function_id: str              # GCS-KV key of the cloudpickled function
    function_name: str            # human-readable, for errors
    args_payload: bytes           # SerializedObject.to_payload() of (args, kwargs)
    num_returns: int
    owner_address: str            # core service addr of the submitting process
    resources: dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    # Actor-task fields
    actor_id: ActorID | None = None
    method_name: str = ""
    sequence_no: int = -1         # per-submitter ordering for actor tasks
    # Named executor pool this call runs in (ref: ConcurrencyGroupManager,
    # src/ray/core_worker/task_execution/concurrency_group_manager.h)
    concurrency_group: str = ""
    # Placement-group routing
    placement_group_id: "object | None" = None
    placement_group_bundle_index: int = -1
    # Wire-form runtime env (see _private/runtime_env.py)
    runtime_env: dict | None = None
    # Exact-match node-label constraint (ref: label_selector,
    # src/ray/common/scheduling/label_selector.h)
    label_selector: dict | None = None
    # Wire form of the scheduling strategy (None = hybrid default,
    # "SPREAD", or {"kind": "node_affinity", ...}; ref: the raylet
    # policy set, composite_scheduling_policy.h:33)
    scheduling_strategy: "dict | str | None" = None
    # Propagated trace context (observability/tracing_plane.py wire
    # tuple (trace_id, span_id, sampled)); None when the submission is
    # not part of a sampled trace — the zero-overhead common case.
    trace_ctx: "tuple | None" = None
    # Execution attempt (0 = first).  Mutated by the submitter before
    # each (re)push so the worker's task events and span ids can tell a
    # retry from the original run (span-id salt).
    attempt: int = 0

    def __reduce__(self):
        # Positional-tuple pickling: the default dataclass path pickles
        # a 19-key dict whose field-name strings are re-encoded in every
        # RPC frame (each frame is a fresh dumps with an empty memo) —
        # measurable at 10k specs/s on the actor-call hot path.
        return (TaskSpec, (
            self.task_id, self.function_id, self.function_name,
            self.args_payload, self.num_returns, self.owner_address,
            self.resources, self.max_retries, self.retry_exceptions,
            self.actor_id, self.method_name, self.sequence_no,
            self.concurrency_group, self.placement_group_id,
            self.placement_group_bundle_index, self.runtime_env,
            self.label_selector, self.scheduling_strategy,
            self.trace_ctx, self.attempt))


@dataclass
class ActorSpec:
    actor_id: ActorID
    class_id: str                 # GCS-KV key of the cloudpickled class
    class_name: str
    args_payload: bytes
    owner_address: str
    # Held for the actor's lifetime (default: none).
    resources: dict[str, float] = field(default_factory=dict)
    # Matched at scheduling time (default: 1 CPU).
    placement_resources: dict[str, float] = field(default_factory=dict)
    max_restarts: int = 0
    max_concurrency: int = 1
    # name -> pool size; methods opt in via @method(concurrency_group=...)
    concurrency_groups: dict[str, int] | None = None
    name: str = ""
    namespace: str = "default"
    lifetime: str | None = None
    job_id: JobID | None = None
    placement_group_id: "object | None" = None
    placement_group_bundle_index: int = -1
    runtime_env: dict | None = None
    label_selector: dict | None = None
    # Wire-form scheduling strategy (see TaskSpec.scheduling_strategy).
    scheduling_strategy: "dict | str | None" = None


@dataclass
class NodeInfo:
    node_id: NodeID
    address: str                  # node daemon RPC addr
    total_resources: dict[str, float] = field(default_factory=dict)
    available_resources: dict[str, float] = field(default_factory=dict)
    object_store_dir: str = ""
    alive: bool = True
    labels: dict[str, str] = field(default_factory=dict)
    # Filesystem-monitor state: a disk-full node keeps its membership
    # but is skipped by scheduling (ref: file_system_monitor.h).
    disk_full: bool = False
    # Drain state (ref: DrainNode / NodeDeathInfo in gcs.proto —
    # announced departures: TPU maintenance events, autoscaler
    # downscale, SIGTERM).  A DRAINING node keeps running its current
    # work but takes no new leases/bundles; schedulers skip it and
    # controllers migrate gangs/replicas off it before the deadline.
    draining: bool = False
    drain_reason: str = ""
    # Wall-clock (time.time()) by which the node expects to be gone;
    # 0.0 = no announced deadline.
    drain_deadline: float = 0.0


# Actor lifecycle states (ref: gcs_actor_manager state machine)
ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"
