"""Central jax import point.

Some environments pin the platform through plugins/sitecustomize in ways
that ignore JAX_PLATFORMS (e.g. a TPU tunnel plugin); honoring our own
``ART_JAX_PLATFORM`` via jax.config *after* import is the reliable
override.  Every module in this package imports jax through
:func:`import_jax` so tests can force the virtual CPU mesh while the same
process tree defaults to the real TPU elsewhere.
"""

from __future__ import annotations

import os

_configured = False


def import_jax():
    global _configured
    import jax  # noqa: PLC0415

    if not _configured:
        # JAX_PLATFORMS alone is not reliable here: a site plugin (e.g.
        # the axon TPU tunnel) can still initialize eagerly and stall for
        # minutes when the tunnel is down; the config-level update is.
        platform = (os.environ.get("ART_JAX_PLATFORM")
                    or os.environ.get("JAX_PLATFORMS"))
        if platform:
            try:
                jax.config.update("jax_platforms", platform)
            except Exception:  # noqa: BLE001 — backend already initialized
                pass
        _configured = True
    return jax


def shard_map():
    """The shard_map entry point across jax versions."""
    import_jax()
    try:
        from jax import shard_map as fn  # noqa: PLC0415
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map as fn  # noqa: PLC0415
    return fn
